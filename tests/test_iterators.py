"""Iterator + except-hook tests (reference: tests/iterators tests and
global_except_hook behavior)."""

import subprocess
import sys

import pytest

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.iterators import (
    create_multi_node_iterator,
    create_synchronized_iterator,
)


def test_multi_node_iterator_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    batches = [1, 2, 3]
    it = create_multi_node_iterator(batches, comm)
    assert list(it) == [1, 2, 3]


def test_synchronized_iterator_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    it = create_synchronized_iterator([5, 6], comm)
    assert list(it) == [5, 6]


def test_global_except_hook_exits_loudly():
    """The crash barrier must exit with its distinct code and print the
    banner (run in a subprocess; the hook calls os._exit)."""
    code = (
        "import chainermn_tpu.global_except_hook as h\n"
        "h.add_hook()\n"
        "raise RuntimeError('boom')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 13
    assert "aborting this host" in proc.stderr
    assert "boom" in proc.stderr


def test_global_except_hook_install_remove():
    import sys as _sys

    import chainermn_tpu.global_except_hook as h

    h.add_hook()
    assert _sys.excepthook is h._handle_uncaught
    h.remove_hook()
    assert _sys.excepthook is _sys.__excepthook__


# ---------------------------------------------------------------------------
# create_prefetch_iterator (reference: MultiprocessIterator overlap)
# ---------------------------------------------------------------------------


def test_prefetch_preserves_order_and_content():
    import jax
    import numpy as np

    from chainermn_tpu.iterators import create_prefetch_iterator

    batches = [
        (np.full((4, 3), i, np.float32), np.full((4,), i, np.int32))
        for i in range(10)
    ]
    out = list(create_prefetch_iterator(iter(batches), size=3))
    assert len(out) == 10
    for i, (x, y) in enumerate(out):
        assert isinstance(x, jax.Array)  # staged onto device
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
        np.testing.assert_array_equal(np.asarray(y), batches[i][1])


def test_prefetch_overlaps_producer_work():
    """The producer thread must run ahead of the consumer: a slow consumer
    should find later batches already produced (queue non-empty)."""
    import time as _time

    import numpy as np

    from chainermn_tpu.iterators import create_prefetch_iterator

    produced = []

    def gen():
        for i in range(5):
            produced.append(i)
            yield np.full((2,), i, np.float32)

    it = create_prefetch_iterator(gen(), size=4)
    first = next(it)
    _time.sleep(0.5)  # consumer stalls; producer should have run ahead
    assert len(produced) >= 4
    rest = list(it)
    assert len(rest) == 4
    np.testing.assert_array_equal(np.asarray(first), np.zeros((2,)))


def test_prefetch_propagates_producer_exception():
    import numpy as np
    import pytest as _pytest

    from chainermn_tpu.iterators import create_prefetch_iterator

    def gen():
        yield np.zeros((2,), np.float32)
        raise RuntimeError("producer exploded")

    it = create_prefetch_iterator(gen(), size=2)
    next(it)
    with _pytest.raises(RuntimeError, match="producer exploded"):
        next(it)


def test_prefetch_with_sharding():
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu.communicators import build_mesh
    from chainermn_tpu.iterators import create_prefetch_iterator

    mesh = build_mesh()
    sh = NamedSharding(mesh, P(("inter", "intra")))
    n = len(jax.devices())
    batches = [np.arange(n * 2, dtype=np.float32).reshape(n * 2, 1)]
    (out,) = list(create_prefetch_iterator(iter(batches), size=1, sharding=sh))
    assert out.sharding == sh


def test_prefetch_rejects_bad_size():
    import pytest as _pytest

    from chainermn_tpu.iterators import create_prefetch_iterator

    with _pytest.raises(ValueError, match="size"):
        create_prefetch_iterator(iter([]), size=0)


def test_prefetch_shutdown_on_abandon():
    """Breaking out of the consuming loop must stop the producer thread and
    drain queued batches (no leaked thread spinning in q.put)."""
    import threading
    import time as _time

    import numpy as np

    from chainermn_tpu.iterators import create_prefetch_iterator

    n_before = threading.active_count()

    def gen():
        for i in range(100):
            yield np.full((2,), i, np.float32)

    it = create_prefetch_iterator(gen(), size=2)
    next(it)
    it.close()  # what GC of an abandoned iterator does
    deadline = _time.time() + 5
    while threading.active_count() > n_before and _time.time() < deadline:
        _time.sleep(0.05)
    assert threading.active_count() <= n_before


# ---------------------------------------------------------------------------
# MultiprocessBatchLoader (reference: Chainer's MultiprocessIterator feeding
# the ImageNet example — worker processes + shared-memory staging)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mp_loader_ds():
    from chainermn_tpu.datasets.toy import SyntheticImageDataset

    return SyntheticImageDataset(n=64, shape=(8, 8))


@pytest.mark.slow
def test_mp_loader_matches_batch_iterator(mp_loader_ds):
    """Same (shuffle, seed, drop_last) → byte-identical batches in the same
    order as the single-process oracle, across repeated passes and after an
    abandoned mid-pass iteration."""
    import numpy as np

    from chainermn_tpu.datasets.multiprocess_iterator import (
        MultiprocessBatchLoader,
    )
    from chainermn_tpu.datasets.toy import batch_iterator

    ref = list(batch_iterator(mp_loader_ds, 16, shuffle=True, seed=3))
    with MultiprocessBatchLoader(
        mp_loader_ds, 16, n_workers=2, shuffle=True, seed=3
    ) as ld:
        assert len(ld) == len(ref) == 4
        got = list(ld)
        assert len(got) == 4
        for (rx, ry), (gx, gy) in zip(ref, got):
            np.testing.assert_array_equal(rx, gx)
            np.testing.assert_array_equal(ry, gy)
        # abandon a pass mid-way, then a fresh pass must still be exact
        it = iter(ld)
        next(it)
        del it
        got2 = list(ld)
        np.testing.assert_array_equal(got2[-1][0], ref[-1][0])


@pytest.mark.slow
def test_mp_loader_repeat_reshuffles_and_zero_copy(mp_loader_ds):
    """repeat=True crosses epoch boundaries reshuffling with seed+epoch;
    copy=False batches are exact while within the validity window."""
    import numpy as np

    from chainermn_tpu.datasets.multiprocess_iterator import (
        MultiprocessBatchLoader,
    )

    ds = mp_loader_ds
    with MultiprocessBatchLoader(
        ds, 16, n_workers=2, repeat=True, copy=False, seed=3
    ) as ld:
        it = iter(ld)
        for k in range(9):  # epoch boundary at k=4
            x, y = next(it)
            epoch, j = divmod(k, 4)
            order = np.random.RandomState(3 + epoch).permutation(64)
            idx = order[j * 16 : (j + 1) * 16]
            np.testing.assert_array_equal(
                x, np.stack([ds[int(i)][0] for i in idx])
            )
            np.testing.assert_array_equal(
                y, np.stack([ds[int(i)][1] for i in idx])
            )


@pytest.mark.slow
def test_mp_loader_worker_exception_propagates(mp_loader_ds):
    from chainermn_tpu.datasets.multiprocess_iterator import (
        MultiprocessBatchLoader,
    )
    from chainermn_tpu.datasets.toy import ExplodingDataset

    bad = ExplodingDataset(mp_loader_ds, explode_at=7)
    with MultiprocessBatchLoader(
        bad, 16, n_workers=2, shuffle=False, seed=0
    ) as ld:
        with pytest.raises(RuntimeError, match="synthetic item failure"):
            list(ld)


@pytest.mark.slow
def test_mp_loader_clean_shutdown(mp_loader_ds):
    """close() must terminate every worker process and release the shared
    memory (no leaked processes; slots unlinked)."""
    import time as _time

    from chainermn_tpu.datasets.multiprocess_iterator import (
        MultiprocessBatchLoader,
    )

    ld = MultiprocessBatchLoader(mp_loader_ds, 16, n_workers=2)
    procs = list(ld._procs)
    it = iter(ld)
    next(it)  # workers mid-stream
    ld.close()
    deadline = _time.time() + 10
    while any(p.is_alive() for p in procs) and _time.time() < deadline:
        _time.sleep(0.05)
    assert not any(p.is_alive() for p in procs)
    assert ld._shms == []
    with pytest.raises(RuntimeError, match="closed"):
        iter(ld)


def test_mp_loader_len_and_empty_guards(mp_loader_ds):
    """repeat=True has no length (infinite); empty datasets are rejected
    eagerly with a clear error rather than a bare IndexError from _probe."""
    from chainermn_tpu.datasets.multiprocess_iterator import (
        MultiprocessBatchLoader,
    )

    with MultiprocessBatchLoader(
        mp_loader_ds, 16, n_workers=1, repeat=True
    ) as ld:
        with pytest.raises(TypeError, match="infinite"):
            len(ld)
    with pytest.raises(ValueError, match="empty"):
        MultiprocessBatchLoader([], 4, drop_last=False)
