"""Iterator + except-hook tests (reference: tests/iterators tests and
global_except_hook behavior)."""

import subprocess
import sys

import pytest

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.iterators import (
    create_multi_node_iterator,
    create_synchronized_iterator,
)


def test_multi_node_iterator_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    batches = [1, 2, 3]
    it = create_multi_node_iterator(batches, comm)
    assert list(it) == [1, 2, 3]


def test_synchronized_iterator_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    it = create_synchronized_iterator([5, 6], comm)
    assert list(it) == [5, 6]


def test_global_except_hook_exits_loudly():
    """The crash barrier must exit with its distinct code and print the
    banner (run in a subprocess; the hook calls os._exit)."""
    code = (
        "import chainermn_tpu.global_except_hook as h\n"
        "h.add_hook()\n"
        "raise RuntimeError('boom')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 13
    assert "aborting this host" in proc.stderr
    assert "boom" in proc.stderr


def test_global_except_hook_install_remove():
    import sys as _sys

    import chainermn_tpu.global_except_hook as h

    h.add_hook()
    assert _sys.excepthook is h._handle_uncaught
    h.remove_hook()
    assert _sys.excepthook is _sys.__excepthook__
