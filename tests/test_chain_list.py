"""MultiNodeChainList tests, mirroring the reference's
tests/links_tests/test_multi_node_chain_list.py (SURVEY §4): a model split
across ranks must match the same model composed on one device, in both
forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.links import MultiNodeChainList


def dense(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_params(rng, d_in, d_out):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (d_in, d_out)) * 0.3,
        "b": jax.random.normal(k2, (d_out,)) * 0.1,
    }


def test_two_stage_forward_matches_composition(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    rng = jax.random.PRNGKey(0)
    p0 = make_params(rng, 4, 8)
    p1 = make_params(jax.random.PRNGKey(1), 8, 2)

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=n - 1)
    chain.add_link(dense, rank=n - 1, rank_in=0, rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 4))
    out = fwd((p0, p1), x)

    expected = dense(p1, dense(p0, x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_two_stage_gradients_match_composition(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    p0 = make_params(jax.random.PRNGKey(0), 4, 8)
    p1 = make_params(jax.random.PRNGKey(1), 8, 2)
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 4))

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=n - 1)
    chain.add_link(dense, rank=n - 1, rank_in=0, rank_out=None)

    def dist_loss(params_list):
        fwd = chain.make_forward(batch_spec=P(), jit=False)
        return jnp.sum(fwd(params_list, x) ** 2)

    def ref_loss(params_list):
        p0, p1 = params_list
        return jnp.sum(dense(p1, dense(p0, x)) ** 2)

    g_dist = jax.jit(jax.grad(dist_loss))((p0, p1))
    g_ref = jax.grad(ref_loss)((p0, p1))
    for gd, gr in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_three_stage_pipeline(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    if n < 3:
        pytest.skip("needs >= 3 devices")
    sizes = [(4, 8), (8, 8), (8, 3)]
    params = [make_params(jax.random.PRNGKey(i), a, b) for i, (a, b) in enumerate(sizes)]

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=1)
    chain.add_link(dense, rank=1, rank_in=0, rank_out=2)
    chain.add_link(dense, rank=2, rank_in=1, rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(9), (6, 4))
    out = fwd(tuple(params), x)
    expected = dense(params[2], dense(params[1], dense(params[0], x)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_branching_multi_recv(mesh):
    """A component with two rank_in sources (the reference supports
    multi-input components via delegate merging)."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    if n < 3:
        pytest.skip("needs >= 3 devices")
    pa = make_params(jax.random.PRNGKey(0), 4, 6)
    pb = make_params(jax.random.PRNGKey(1), 4, 6)

    def merge(params, xs):
        a, b = xs
        return a + b

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=2)
    chain.add_link(dense, rank=1, rank_in=None, rank_out=2)
    chain.add_link(merge, rank=2, rank_in=(0, 1), rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 4))
    out = fwd((pa, pb, ()), x)
    expected = dense(pa, x) + dense(pb, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_miswired_chain_fails_at_trace_time(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=1, rank_in=0, rank_out=None)  # recv with no send
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="no send"):
        fwd((make_params(jax.random.PRNGKey(0), 4, 4),), jnp.ones((2, 4)))


def test_no_output_component_raises(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=1)
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="rank_out=None"):
        fwd((make_params(jax.random.PRNGKey(0), 4, 4),), jnp.ones((2, 4)))


def test_params_length_mismatch_raises(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=None)
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="components"):
        fwd((), jnp.ones((2, 4)))
