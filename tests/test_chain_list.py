"""MultiNodeChainList tests, mirroring the reference's
tests/links_tests/test_multi_node_chain_list.py (SURVEY §4): a model split
across ranks must match the same model composed on one device, in both
forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.links import MultiNodeChainList


def dense(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_params(rng, d_in, d_out):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (d_in, d_out)) * 0.3,
        "b": jax.random.normal(k2, (d_out,)) * 0.1,
    }


def test_two_stage_forward_matches_composition(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    rng = jax.random.PRNGKey(0)
    p0 = make_params(rng, 4, 8)
    p1 = make_params(jax.random.PRNGKey(1), 8, 2)

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=n - 1)
    chain.add_link(dense, rank=n - 1, rank_in=0, rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 4))
    out = fwd((p0, p1), x)

    expected = dense(p1, dense(p0, x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_two_stage_gradients_match_composition(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    p0 = make_params(jax.random.PRNGKey(0), 4, 8)
    p1 = make_params(jax.random.PRNGKey(1), 8, 2)
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 4))

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=n - 1)
    chain.add_link(dense, rank=n - 1, rank_in=0, rank_out=None)

    def dist_loss(params_list):
        fwd = chain.make_forward(batch_spec=P(), jit=False)
        return jnp.sum(fwd(params_list, x) ** 2)

    def ref_loss(params_list):
        p0, p1 = params_list
        return jnp.sum(dense(p1, dense(p0, x)) ** 2)

    g_dist = jax.jit(jax.grad(dist_loss))((p0, p1))
    g_ref = jax.grad(ref_loss)((p0, p1))
    for gd, gr in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_three_stage_pipeline(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    if n < 3:
        pytest.skip("needs >= 3 devices")
    sizes = [(4, 8), (8, 8), (8, 3)]
    params = [make_params(jax.random.PRNGKey(i), a, b) for i, (a, b) in enumerate(sizes)]

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=1)
    chain.add_link(dense, rank=1, rank_in=0, rank_out=2)
    chain.add_link(dense, rank=2, rank_in=1, rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(9), (6, 4))
    out = fwd(tuple(params), x)
    expected = dense(params[2], dense(params[1], dense(params[0], x)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_branching_multi_recv(mesh):
    """A component with two rank_in sources (the reference supports
    multi-input components via delegate merging)."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    if n < 3:
        pytest.skip("needs >= 3 devices")
    pa = make_params(jax.random.PRNGKey(0), 4, 6)
    pb = make_params(jax.random.PRNGKey(1), 4, 6)

    def merge(params, xs):
        a, b = xs
        return a + b

    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=2)
    chain.add_link(dense, rank=1, rank_in=None, rank_out=2)
    chain.add_link(merge, rank=2, rank_in=(0, 1), rank_out=None)

    fwd = chain.make_forward(batch_spec=P())
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 4))
    out = fwd((pa, pb, ()), x)
    expected = dense(pa, x) + dense(pb, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def _hetero_chain(comm, n):
    """Encoder/decoder-shaped chain with different widths per stage —
    the seq2seq profile the sharded tier exists for."""
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=n - 1)
    chain.add_link(dense, rank=n - 1, rank_in=0, rank_out=None)
    p0 = make_params(jax.random.PRNGKey(0), 4, 16)   # encoder: 4*16+16
    p1 = make_params(jax.random.PRNGKey(1), 16, 2)   # decoder: 16*2+2
    return chain, (p0, p1)


def test_sharded_forward_matches_replicated(mesh):
    """VERDICT r1 item 8: the sharded tier reproduces the replicated
    forward exactly while each device persistently holds only its own
    components' parameters."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    chain, params_list = _hetero_chain(comm, n)
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 4))

    flat = chain.shard_params(params_list)

    # Memory profile: global buffer is n * row_size with row_size = the
    # LARGEST per-device stage, not the total model.
    sizes = [sum(l.size for l in jax.tree.leaves(p)) for p in params_list]
    total = sum(sizes)
    row_size = chain._shard_meta[2]
    assert row_size == max(sizes) < total
    assert flat.shape == (n * row_size,)
    # Each device's resident shard is exactly one row.
    shard = flat.addressable_shards[0]
    assert shard.data.size == row_size
    # Replicated tier would hold `total` floats per device; this holds
    # max-stage floats per device.
    assert row_size * flat.dtype.itemsize < total * 4

    world = chain._world
    fwd = jax.jit(comm.shard_map(
        chain.apply_sharded, in_specs=(P(world), P()), out_specs=P()
    ))
    out = fwd(flat, x)
    expected = dense(params_list[1], dense(params_list[0], x))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6
    )

    # materialize round-trips the pytrees.
    back = chain.materialize_params(flat)
    for p, b in zip(params_list, back):
        for k in p:
            np.testing.assert_allclose(
                np.asarray(b[k]), np.asarray(p[k]), rtol=1e-6, atol=1e-7
            )


def test_sharded_training_matches_replicated(mesh):
    """A seq2seq-shaped chain trains in the sharded tier with the same
    trajectory as replicated-parameter training (same optimizer, same
    batches): stage-sharded storage changes memory, not math."""
    import optax

    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    chain, params_list = _hetero_chain(comm, n)
    x = jax.random.normal(jax.random.PRNGKey(2), (6, 4))
    y = jax.random.normal(jax.random.PRNGKey(3), (6, 2))
    batch = {"x": x, "y": y}

    def loss_fn(out, batch):
        return jnp.mean((out - batch["y"]) ** 2)

    # The chain consumes batch["x"] as its input.
    chain2, _ = _hetero_chain(comm, n)
    chain2._components[0] = chain2._components[0]._replace(
        fn=lambda p, b: dense(p, b["x"])
    )

    opt = optax.adam(1e-2)
    flat = chain2.shard_params(params_list)
    opt_state = chain2.init_sharded_opt_state(opt, flat)
    step = chain2.make_sharded_train_step(opt, loss_fn, donate=False)

    # Replicated oracle: same chain math on replicated pytrees (fp32
    # master semantics to match the row buffer).
    def rep_loss(plist):
        out = dense(plist[1], dense(plist[0], x))
        return jnp.mean((out - y) ** 2)

    rep_params = jax.tree.map(lambda l: l.astype(jnp.float32), params_list)
    rep_state = opt.init(rep_params)

    losses = []
    for _ in range(4):
        flat, opt_state, loss = step(flat, opt_state, batch)
        losses.append(float(loss))
        g = jax.grad(rep_loss)(rep_params)
        up, rep_state = opt.update(g, rep_state, rep_params)
        rep_params = optax.apply_updates(rep_params, up)

    assert losses[-1] < losses[0]
    got = chain2.materialize_params(flat)
    for p_ref, p_got in zip(rep_params, got):
        for k in p_ref:
            np.testing.assert_allclose(
                np.asarray(p_got[k]), np.asarray(p_ref[k]),
                rtol=1e-4, atol=1e-5,
            )


def test_miswired_chain_fails_at_trace_time(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=1, rank_in=0, rank_out=None)  # recv with no send
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="no send"):
        fwd((make_params(jax.random.PRNGKey(0), 4, 4),), jnp.ones((2, 4)))


def test_no_output_component_raises(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=1)
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="rank_out=None"):
        fwd((make_params(jax.random.PRNGKey(0), 4, 4),), jnp.ones((2, 4)))


def test_params_length_mismatch_raises(mesh):
    comm = create_communicator("naive", mesh=mesh)
    chain = MultiNodeChainList(comm)
    chain.add_link(dense, rank=0, rank_in=None, rank_out=None)
    fwd = chain.make_forward(batch_spec=P(), jit=False)
    with pytest.raises(ValueError, match="components"):
        fwd((), jnp.ones((2, 4)))
