"""Host-plane transport unit tests (CPU tier, no jax.distributed).

The cross-process integration runs in tests/test_multiprocess.py; here the
SocketPlane's framing/routing/matching logic is exercised in one process
with a dict-backed fake of the coordination-service KV client (rendezvous
only — the data rides real loopback TCP sockets), mirroring how the
reference unit-tested transport-adjacent logic without mpiexec (SURVEY §4).
"""

import threading
import time

import numpy as np
import pytest

from chainermn_tpu.communicators import kvtransport as kv


class FakeKvClient:
    """Rendezvous-only stand-in for the jax.distributed KV client."""

    def __init__(self):
        self.d = {}
        self.cv = threading.Condition()

    def key_value_set(self, k, v):
        with self.cv:
            self.d[k] = v
            self.cv.notify_all()

    def blocking_key_value_get(self, k, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1e3
        with self.cv:
            while k not in self.d:
                left = deadline - time.monotonic()
                if left <= 0 or not self.cv.wait(timeout=left):
                    raise RuntimeError("DEADLINE_EXCEEDED (fake)")
            return self.d[k]


@pytest.fixture
def sock_pair(monkeypatch):
    fake = FakeKvClient()
    monkeypatch.setattr(kv, "client", lambda: fake)
    return kv.SocketPlane(0), kv.SocketPlane(1)


def test_socket_plane_typed_roundtrip(sock_pair):
    """Every payload shape the typed path distinguishes — multi-frame
    float64, 0-d scalar, non-contiguous view, empty array, pickled dict —
    arrives in order with exact dtype/shape/values."""
    p0, p1 = sock_pair
    typed = np.random.RandomState(11).randn(100_001)
    msgs = [
        typed,
        np.array(2.5, np.float32),
        typed[:99].reshape(33, 3)[:, 1],  # non-contiguous view
        np.empty((0, 4), np.int16),
        {"obj": 1, "nested": [1, 2]},
    ]
    for seq, m in enumerate(msgs):
        p0.send("c", 1, 9, seq, m)
    for seq, m in enumerate(msgs):
        got = p1.recv("c", 0, 9, seq, timeout_ms=20000)
        if isinstance(m, np.ndarray):
            assert isinstance(got, np.ndarray)
            assert got.shape == m.shape and got.dtype == m.dtype
            np.testing.assert_array_equal(got, m)
        else:
            assert got == m


def test_socket_plane_routes_by_namespace_and_tag(sock_pair):
    """Messages on different (namespace, tag) routes do not interleave:
    a recv on one route sees only its own stream, whatever the arrival
    order across routes."""
    p0, p1 = sock_pair
    p0.send("commA", 1, 0, 0, "a0")
    p0.send("commB", 1, 0, 0, "b0")
    p0.send("commA", 1, 5, 0, "a-tag5")
    p0.send("commA", 1, 0, 1, "a1")
    assert p1.recv("commA", 0, 5, 0, timeout_ms=20000) == "a-tag5"
    assert p1.recv("commB", 0, 0, 0, timeout_ms=20000) == "b0"
    assert p1.recv("commA", 0, 0, 0, timeout_ms=20000) == "a0"
    assert p1.recv("commA", 0, 0, 1, timeout_ms=20000) == "a1"


def test_socket_plane_timeout_is_retryable(sock_pair):
    """A timed-out recv leaves the stream intact: the late message is
    delivered by the retry (the recv_obj retry contract)."""
    p0, p1 = sock_pair
    with pytest.raises(TimeoutError):
        p1.recv("c", 0, 3, 0, timeout_ms=100)
    p0.send("c", 1, 3, 0, np.arange(5))
    got = p1.recv("c", 0, 3, 0, timeout_ms=20000)
    np.testing.assert_array_equal(got, np.arange(5))


def test_socket_plane_detects_seq_desync(sock_pair):
    """A receiver expecting the wrong sequence number fails fast with a
    diagnostic instead of silently delivering the wrong payload."""
    p0, p1 = sock_pair
    p0.send("c", 1, 4, 0, "first")
    with pytest.raises(RuntimeError, match="desync"):
        p1.recv("c", 0, 4, 7, timeout_ms=20000)


def test_payload_header_roundtrip_dtypes(monkeypatch):
    """put_payload/get_payload over a full fake KV store (bytes values
    too): typed arrays of assorted dtypes and the pickle fallback."""

    class FullFake(FakeKvClient):
        def key_value_set_bytes(self, k, v):
            self.key_value_set(k, bytes(v))

        def blocking_key_value_get_bytes(self, k, timeout_ms):
            return self.blocking_key_value_get(k, timeout_ms)

        def key_value_delete(self, k):
            with self.cv:
                self.d.pop(k, None)

    fake = FullFake()
    monkeypatch.setattr(kv, "client", lambda: fake)
    cases = [
        np.arange(10, dtype=np.int64),
        np.zeros((3, 0, 2), np.float16),
        np.array(b"x"),  # bytes_ dtype — still typed
        np.random.RandomState(0).randn(kv.CHUNK_BYTES // 8 + 7),  # 2 chunks
        ["not", "an", "array"],
    ]
    for i, c in enumerate(cases):
        kv.put_payload(f"k{i}", c)
        got, _n = kv.get_payload(f"k{i}", timeout_ms=5000)
        if isinstance(c, np.ndarray):
            assert got.shape == c.shape and got.dtype == c.dtype
            np.testing.assert_array_equal(got, c)
        else:
            assert got == c


def test_socket_plane_rejects_unauthenticated_connection(sock_pair):
    """A connection that does not open with the secret token must be
    dropped before any frame is processed (frames can carry pickles)."""
    import socket as _socket
    import struct, pickle, time as _time

    p0, p1 = sock_pair
    host, port, _token = kv.client().d[f"{kv._PREFIX}/sockep/1"].rsplit(":", 2)
    evil = _socket.create_connection((host, int(port)))
    payload = pickle.dumps("evil")
    hdr = (
        b'{"kind": "pkl", "nbytes": %d, "ns": "c", "src": 0, "tag": 0, "seq": 0}'
        % len(payload)
    )
    try:
        evil.sendall(b"\x00" * kv.TOKEN_BYTES)  # wrong token
        evil.sendall(struct.pack("<I", len(hdr)) + hdr + payload)
    except OSError:
        pass  # already dropped — also a pass
    # The frame must never be routed; a legitimate message still flows.
    with pytest.raises(TimeoutError):
        p1.recv("c", 0, 0, 0, timeout_ms=300)
    p0.send("c", 1, 0, 0, "legit")
    assert p1.recv("c", 0, 0, 0, timeout_ms=20000) == "legit"
    evil.close()


def test_malformed_frame_poisons_recv_not_hangs(sock_pair):
    """A malformed frame (bogus header) must not kill the reader thread
    silently: pending and future recvs raise a transport RuntimeError
    promptly instead of hanging to their timeout (ADVICE r3 #3)."""
    import socket as _socket
    import struct
    import time as _time

    p0, p1 = sock_pair
    # Park a payload on one route first so its queue exists, then a
    # blocked reader on another route.
    p0.send("c", 1, 1, 0, "parked")
    assert p1.recv("c", 0, 1, 0, timeout_ms=20000) == "parked"

    # Hand-craft a corrupt frame on a fresh authenticated connection:
    # nbytes wildly inconsistent with dtype/shape.
    ep = p1._srv.getsockname()
    conn = _socket.create_connection(ep)
    conn.sendall(p1._token)
    hdr = (
        b'{"kind": "nd", "dtype": "<f4", "shape": [4], '
        b'"nbytes": 999999999999, "ns": "c", "src": 0, "tag": 2, "seq": 0}'
    )
    conn.sendall(struct.pack("<I", len(hdr)) + hdr)

    deadline = _time.monotonic() + 10
    while p1._broken is None and _time.monotonic() < deadline:
        _time.sleep(0.02)
    assert p1._broken is not None and "nbytes" in p1._broken
    # Existing-route recv fails fast (poisoned), not by timeout.
    t0 = _time.monotonic()
    with pytest.raises(RuntimeError, match="died decoding"):
        p1.recv("c", 0, 1, 1, timeout_ms=60_000)
    assert _time.monotonic() - t0 < 5
    # New-route recv also fails fast via the _broken check.
    with pytest.raises(RuntimeError, match="died decoding"):
        p1.recv("c", 0, 99, 0, timeout_ms=60_000)
    conn.close()


def test_oversized_send_raises_on_sender(sock_pair, monkeypatch):
    """A payload above MAX_FRAME_BYTES fails loudly on the SENDING rank
    with an actionable error instead of poisoning the receiver."""
    p0, _p1 = sock_pair
    monkeypatch.setattr(kv, "MAX_FRAME_BYTES", 1024)
    with pytest.raises(ValueError, match="CHAINERMN_TPU_MAX_FRAME_BYTES"):
        p0.send("c", 1, 0, 0, np.zeros(4096, np.float64))


def test_object_plane_gather_root_timeout(monkeypatch):
    """ADVICE r4: point-to-root gather must honor timeout_ms at root so a
    member that died before sending raises instead of blocking forever.
    KV-fallback path (sockets off), dict-backed fake KV, member 1 never
    sends."""
    from jax.errors import JaxRuntimeError

    class FullFake(FakeKvClient):
        def blocking_key_value_get(self, k, timeout_ms):
            # Mimic the real client's deadline surface (the gRPC
            # DEADLINE_EXCEEDED status as a JaxRuntimeError) so
            # _is_deadline recognizes it and _blocking_get translates
            # expiry to TimeoutError.
            try:
                return super().blocking_key_value_get(k, timeout_ms)
            except RuntimeError as e:
                raise JaxRuntimeError(str(e)) from None

        def key_value_set_bytes(self, k, v):
            self.key_value_set(k, bytes(v))

        def blocking_key_value_get_bytes(self, k, timeout_ms):
            return self.blocking_key_value_get(k, timeout_ms)

        def key_value_delete(self, k):
            with self.cv:
                self.d.pop(k, None)

    fake = FullFake()
    monkeypatch.setattr(kv, "client", lambda: fake)
    monkeypatch.setattr(kv, "available", lambda: True)
    monkeypatch.setattr(kv.ObjectPlane, "_use_sockets", False)
    root = kv.ObjectPlane("gt", rank=0, size=2, site="t:1")
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):  # same type as the socket plane's
        root.gather("root-obj", 0, timeout_ms=300)
    assert time.monotonic() - t0 < 10.0  # bounded, not a hang


# ---------------------------------------------------------------------------
# Peer-death churn: PeerGone, queued-message delivery, re-handshake
# ---------------------------------------------------------------------------


def test_peer_death_raises_peer_gone_fast(sock_pair):
    """EOF from a connected peer converts blocked/future recvs into
    PeerGone well before the caller's timeout — waiting out a 30 s
    deadline on a corpse is the hang this rules out."""
    p0, p1 = sock_pair
    p0.send("c", 1, 0, 0, "hello")
    assert p1.recv("c", 0, 0, 0, timeout_ms=20_000) == "hello"

    p0._send_socks[1].close()  # peer 0's process "dies"
    t0 = time.monotonic()
    with pytest.raises(kv.PeerGone) as e:
        p1.recv("c", 0, 0, 1, timeout_ms=60_000)
    assert time.monotonic() - t0 < 10
    assert e.value.peer == 0
    assert p1.peer_gone(0) is not None


def test_peer_death_delivers_queued_messages_first(sock_pair):
    """Frames that arrived before the peer died are real data — death
    must not destroy them.  The PeerGone marker queues BEHIND them."""
    p0, p1 = sock_pair
    p0.send("c", 1, 3, 0, "one")
    p0.send("c", 1, 3, 1, "two")
    # Wait until both frames are parked so the close can't race them.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        q = p1._queue(("c", 0, 3))
        if q.qsize() >= 2:
            break
        time.sleep(0.01)
    p0._send_socks[1].close()
    assert p1.recv("c", 0, 3, 0, timeout_ms=20_000) == "one"
    assert p1.recv("c", 0, 3, 1, timeout_ms=20_000) == "two"
    with pytest.raises(kv.PeerGone):
        p1.recv("c", 0, 3, 2, timeout_ms=60_000)


def test_partial_frame_death_is_peer_gone(sock_pair):
    """Death MID-FRAME (header sent, payload truncated) is still clean
    peer death, not a malformed-frame poisoning: the incomplete frame
    is dropped and recv raises PeerGone."""
    import struct

    p0, p1 = sock_pair
    p0.send("c", 1, 4, 0, "intact")
    assert p1.recv("c", 0, 4, 0, timeout_ms=20_000) == "intact"

    sock = p0._send_socks[1]
    hdr = (
        b'{"kind": "pkl", "nbytes": 64, "ns": "c", "src": 0, '
        b'"tag": 4, "seq": 1}'
    )
    sock.sendall(struct.pack("<I", len(hdr)) + hdr + b"\x00" * 10)
    sock.close()  # dies 54 bytes short of its own header's promise
    with pytest.raises(kv.PeerGone):
        p1.recv("c", 0, 4, 1, timeout_ms=60_000)
    assert p1._broken is None  # transport NOT poisoned: peers can talk


def test_replacement_peer_rehandshakes_after_death(sock_pair):
    """After PeerGone, a REPLACEMENT process at the same rank can
    republish its endpoint and resume the stream: the survivor's stale
    gone-markers are skipped, not fatal."""
    p0, p1 = sock_pair
    p0.send("c", 1, 5, 0, "before")
    assert p1.recv("c", 0, 5, 0, timeout_ms=20_000) == "before"
    p0._send_socks[1].close()
    with pytest.raises(kv.PeerGone):
        p1.recv("c", 0, 5, 1, timeout_ms=60_000)

    # Same-rank replacement: a fresh plane re-publishes rank 0's
    # endpoint (delete-then-set) and connects anew.
    p0b = kv.SocketPlane(0)
    p0b.send("c", 1, 5, 1, "after")
    # recv may still fast-fail PeerGone until the reader processes the
    # replacement's first frame — exactly the window retry_backoff is
    # for (send() returning does not mean the survivor routed it yet).
    got = kv.retry_backoff(
        lambda: p1.recv("c", 0, 5, 1, timeout_ms=20_000),
        retries=6, base_s=0.05,
    )
    assert got == "after"
    assert p1.peer_gone(0) is None  # revived
    # The replaced endpoint is the one future connects reach.
    p1.send("c", 0, 6, 0, "to-replacement")
    assert p0b.recv("c", 1, 6, 0, timeout_ms=20_000) == "to-replacement"


def test_send_to_dead_peer_raises_peer_gone(sock_pair, monkeypatch):
    """Connecting to a dead endpoint fails as PeerGone (retryable via
    retry_backoff), not a raw OSError.  The dead endpoint is port 1
    (privileged, never listening, never ephemeral) rather than the
    peer's closed port: on loopback, connecting to a just-freed port
    can land a TCP self-connection when the kernel picks it as the
    ephemeral source port too."""
    p0, p1 = sock_pair
    key = f"{kv._PREFIX}/sockep/1"
    host, _port, token = kv.client().d[key].rsplit(":", 2)
    kv.client().key_value_set(key, f"{host}:1:{token}")
    p0._send_socks.pop(1, None)
    with pytest.raises(kv.PeerGone):
        p0.send("c", 1, 0, 0, "anyone home?")


def test_retry_backoff_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise kv.PeerGone("not yet", peer=7)
        return "ok"

    assert kv.retry_backoff(flaky, retries=4, base_s=0.001) == "ok"
    assert len(calls) == 3
    with pytest.raises(kv.PeerGone):
        kv.retry_backoff(
            lambda: (_ for _ in ()).throw(kv.PeerGone("always")),
            retries=2, base_s=0.001,
        )
