"""Cross-replica request tracing: span trees, the crash-surviving
flight recorder, Chrome-trace export, SLO burn gauges, stragglers.

The contract under test, layer by layer:

1. **Tracer core** — begin/end produce span rows only at ``end`` (the
   crash-robustness rule: an open span is never on disk, so a SIGKILLed
   process loses open spans but never writes a dangling child);
   ``span()`` closes and marks ``error`` on exceptions; double-``end``
   is a no-op; ``token()`` arrivals become the derived ``deliver`` span
   when the root closes.
2. **FlightRecorder** — O_APPEND JSONL that tolerates a torn final
   line (the SIGKILL tail) and skips rotated files on directory reads.
3. **Stitch/validate/export** — orphan detection, timestamp
   monotonicity, and the Chrome-trace JSON schema (golden file).
4. **Serving integration** — a disaggregated 2-replica run yields one
   CONNECTED tree per request with every stage span present, and
   tracing adds ZERO compiles (it never touches jit inputs).
5. **Fleet health** — SLO burn-rate gauges and the straggler detector.

All CPU, in-process.  The cross-process SIGKILL postmortem soaks in
tests/test_multiprocess.py.
"""

import json
import os

import pytest

from chainermn_tpu.observability import tracing
from chainermn_tpu.observability.reporter import Reporter
from chainermn_tpu.observability.tracing import (
    FlightRecorder,
    SLOConfig,
    SpanCtx,
    Tracer,
    detect_stragglers,
    read_flight,
    read_flight_dir,
    stage_percentiles,
    stitch,
    to_chrome_trace,
    validate_trace,
)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "serve_trace.json")


def make_tracer(**kw):
    """Deterministic tracer: fake monotonic clock, fixed id nonce."""
    clock = {"t": 1000.0}

    def tick():
        clock["t"] += 0.001
        return clock["t"]

    kw.setdefault("nonce", "g")
    tr = Tracer(clock=tick, **kw)
    return tr, clock


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

def test_begin_end_emits_rows_only_at_end():
    tr, _ = make_tracer()
    root = tr.begin("request", rid=1)
    assert tr.records() == []          # open spans live in memory only
    assert tr.open_count() == 1
    tr.end(root, status="finished")
    rows = tr.records()
    assert [r["name"] for r in rows] == ["request"]
    assert rows[0]["event"] == "span"
    assert rows[0]["trace"] == root.trace_id
    assert rows[0]["parent"] is None
    assert tr.open_count() == 0


def test_double_end_is_noop():
    tr, _ = make_tracer()
    ctx = tr.begin("request")
    tr.end(ctx)
    tr.end(ctx)
    assert len(tr.records()) == 1


def test_span_contextmanager_closes_and_marks_error():
    tr, _ = make_tracer()
    root = tr.begin("request")
    with pytest.raises(RuntimeError):
        with tr.span("prefill", parent=root, replica=0):
            raise RuntimeError("page fault")
    tr.end(root)
    rows = {r["name"]: r for r in tr.records()}
    assert rows["prefill"]["error"] is True
    assert "page fault" in rows["prefill"]["attrs"]["error_msg"]
    assert tr.open_count() == 0        # nothing leaked open


def test_token_arrivals_become_deliver_span():
    tr, _ = make_tracer()
    root = tr.begin("request")
    tr.token(root)
    tr.token(root)
    tr.token(root)
    tr.end(root, tokens=3)
    rows = {r["name"]: r for r in tr.records()}
    d = rows["deliver"]
    assert d["attrs"]["tokens"] == 3
    assert d["parent"] == root.span_id
    assert d["dur"] == pytest.approx(0.002, abs=1e-6)


def test_record_span_and_event_parent_to_wire_ctx():
    tr, _ = make_tracer()
    root = tr.begin("request")
    wire = SpanCtx.from_wire(root.to_wire())   # the CMD-frame round trip
    assert wire.trace_id == root.trace_id
    tr.record_span("queue", wire, 1000.0, 0.5, replica=2, depth=3)
    tr.event("preempted", wire, replica=2)
    tr.end(root)
    rows = tr.records()
    by = {r["name"]: r for r in rows}
    assert by["queue"]["parent"] == root.span_id
    assert by["queue"]["replica"] == 2
    assert by["preempted"]["event"] == "evt"
    # untraced request: ctx None is a no-op, not an error
    tr.record_span("queue", None, 0.0, 0.1)
    tr.event("preempted", None)
    assert len(tr.records()) == len(rows)


def test_nothing_recorded_when_uninstalled():
    assert tracing.get_tracer() is None
    ctx = tracing.SpanCtx.from_wire(None)
    assert ctx is None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_roundtrip_and_torn_tail(tmp_path):
    p = tmp_path / "flight_0.jsonl"
    tr, _ = make_tracer(flight=FlightRecorder(str(p), replica=0),
                        replica=0)
    root = tr.begin("request", rid=7)
    tr.record_span("prefill", root, 1000.0, 0.25, tokens=8)
    tr.end(root, status="finished")
    tr.close()
    # simulate the SIGKILL torn tail: a half-written final line
    with open(p, "a") as f:
        f.write('{"event": "span", "name": "dec')
    rows = read_flight(str(p))
    assert [r["name"] for r in rows] == ["prefill", "request"]
    assert all(r["replica"] == 0 for r in rows)


def test_read_flight_dir_merges_and_skips_rotated(tmp_path):
    a = tmp_path / "flight_0.jsonl"
    b = tmp_path / "flight_1.jsonl"
    for path, rep in ((a, 0), (b, 1)):
        tr, _ = make_tracer(flight=FlightRecorder(str(path),
                                                  replica=rep),
                            replica=rep, nonce=f"n{rep}")
        root = tr.begin("request")
        tr.end(root)
        tr.close()
    # a rotated shard folds into its parent log — and must not be
    # double-read even when the glob matches it directly
    (tmp_path / "flight_0.jsonl.1").write_text(
        json.dumps({"event": "span", "trace": "tx", "span": "x",
                    "parent": None, "name": "request", "t0": 1.0,
                    "dur": 1.0, "replica": 9}) + "\n"
    )
    rows = read_flight_dir(str(tmp_path / "flight_*"))
    assert sorted({r["replica"] for r in rows}) == [0, 1, 9]
    assert sum(1 for r in rows if r["replica"] == 9) == 1


# ---------------------------------------------------------------------------
# stitch / validate / percentiles
# ---------------------------------------------------------------------------

def _rows(*triples):
    out = []
    for name, sid, parent in triples:
        # the root (parent None) encloses everything; children nest
        dur = 100.0 if parent is None else 0.5
        out.append({"event": "span", "trace": "t1", "span": sid,
                    "parent": parent, "name": name,
                    "t0": 1000.0 + len(out), "dur": dur, "replica": 0})
    return out


def test_validate_flags_orphans():
    good = _rows(("request", "r", None), ("queue", "q", "r"))
    v = validate_trace(stitch(good)["t1"]["spans"])
    assert v["connected"] and not v["orphans"] and v["monotone"]

    bad = _rows(("request", "r", None), ("queue", "q", "GONE"))
    v = validate_trace(stitch(bad)["t1"]["spans"])
    assert not v["connected"]
    assert v["orphans"] == ["q"]


def test_validate_flags_nonmonotone_child():
    rows = _rows(("request", "r", None))
    rows.append({"event": "span", "trace": "t1", "span": "q",
                 "parent": "r", "name": "queue", "t0": 10.0,
                 "dur": 0.1, "replica": 0})  # starts before the root
    v = validate_trace(stitch(rows)["t1"]["spans"])
    assert not v["monotone"]
    assert v["violations"]


def test_stage_percentiles_nearest_rank():
    rows = [
        {"event": "span", "trace": f"t{i}", "span": f"s{i}",
         "parent": None, "name": "decode", "t0": 0.0,
         "dur": (i + 1) / 100.0, "replica": 0}
        for i in range(100)
    ]
    st = stage_percentiles(rows)["decode"]
    assert st["count"] == 100
    assert st["p50_s"] == pytest.approx(0.50)
    assert st["p99_s"] == pytest.approx(0.99)


# ---------------------------------------------------------------------------
# SLO burn + stragglers
# ---------------------------------------------------------------------------

def test_slo_burn_rate_gauges():
    rep = Reporter()
    tr, _ = make_tracer(
        reporter=rep,
        slo=SLOConfig(targets={"decode": 0.01}, budget=0.01, window=8),
    )
    root = tr.begin("request")
    for i in range(8):
        # half the window violates the 10ms decode objective
        tr.record_span("decode", root, 0.0, 0.5 if i % 2 else 0.001,
                       replica=0)
    tr.end(root)
    s = rep.summary()
    assert s["counters"]["slo/violations/decode"] == 4
    # violating fraction 0.5 over budget 0.01 → burn rate 50x
    assert s["gauges"]["slo/burn_rate/decode"]["value"] == \
        pytest.approx(50.0)
    # stage histograms ride along for the Prometheus path
    assert any(k.startswith("trace/decode") for k in s["histograms"])


def test_detect_stragglers_flags_slow_replica():
    stats = {}
    for rep in (0, 1, 2):
        base = 10.0 if rep == 2 else 0.01
        stats[(rep, "decode")] = [base] * 8
        stats[(rep, "prefill")] = [0.02] * 8
    flagged = detect_stragglers(stats, k=4.0, min_samples=4)
    assert set(flagged) == {2}
    assert flagged[2]["decode"] > 4.0
    # a single-replica fleet has no peer baseline — never flags
    assert detect_stragglers({(0, "decode"): [9.9] * 8}) == {}


# ---------------------------------------------------------------------------
# chrome export (golden schema)
# ---------------------------------------------------------------------------

def _synthetic_serve_records():
    """A deterministic disagg-shaped request: router root + placement,
    prefill on replica 0, handoff + decode on replica 1, a preemption
    instant, tokens → deliver.  Fixed clock and nonce make every id and
    timestamp reproducible, so the export can be compared whole."""
    tr, clock = make_tracer(replica="router")
    root = tr.begin("request", rid=0, prompt_len=9, max_new_tokens=3)
    tr.record_span("placement", root, 1000.002, 0.001,
                   replica="router", target=0, kind="prefill")
    tr.record_span("queue", root, 1000.003, 0.004, replica=0, depth=1)
    # a chunked-prefill slice: the long prompt's first pages land
    # between decode iterations before the monolithic remainder
    tr.record_span("prefill_chunk", root, 1000.005, 0.002, replica=0,
                   tokens=4, pos=4, total=9)
    tr.record_span("prefill", root, 1000.008, 0.050, replica=0,
                   tokens=9, disagg=True)
    tr.record_span("handoff", root, 1000.060, 0.010, replica=1,
                   tokens=10)
    tr.event("preempted", root, replica=1, generated=1)
    tr.token(root)
    tr.record_span("decode", root, 1000.080, 0.005, replica=1, batch=2)
    tr.token(root)
    # a speculative iteration: per-request draft proposal, then the
    # batched speculate + verify step (the verify span replaces that
    # iteration's decode span)
    tr.record_span("draft", root, 1000.088, 0.001, replica=1,
                   source="model", draft=2)
    tr.record_span("speculate", root, 1000.089, 0.001, replica=1,
                   draft=2)
    tr.record_span("verify", root, 1000.090, 0.005, replica=1, batch=2,
                   accepted=1)
    tr.token(root)
    tr.end(root, status="finished", tokens=3)
    return tr.records()


def test_chrome_trace_golden():
    doc = to_chrome_trace(_synthetic_serve_records())
    with open(GOLDEN) as f:
        want = json.load(f)
    assert doc == want


def test_chrome_trace_schema_invariants():
    doc = to_chrome_trace(_synthetic_serve_records())
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    phases = {e["ph"] for e in evs}
    assert phases == {"M", "X", "i"}
    # one process-name metadata row per replica, stable pid mapping
    meta = [e for e in evs if e["ph"] == "M"]
    assert len(meta) == len({e["pid"] for e in evs})
    spans = [e for e in evs if e["ph"] == "X"]
    assert all({"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
               for e in spans)
    # every span of one trace shares a tid; ts/dur are microseconds
    tids = {e["args"]["trace"]: e["tid"] for e in spans}
    assert len(set(tids.values())) == len(tids)


# ---------------------------------------------------------------------------
# prometheus export of trace series
# ---------------------------------------------------------------------------

def test_prometheus_trace_series_and_header_dedupe(tmp_path):
    from chainermn_tpu.tools.obs import summarize, to_prometheus

    rows = _synthetic_serve_records()
    # plus a second replica's decode so per-replica labels materialize
    rows.append({"event": "span", "trace": "tg.2", "span": "x1",
                 "parent": "g.1", "name": "decode", "t0": 1000.1,
                 "dur": 0.004, "replica": 0})
    text = to_prometheus(summarize(rows), prefix="t")
    lines = text.splitlines()
    helps = [l for l in lines if l.startswith("# HELP")]
    # satellite: headers are emitted at most once per metric name
    assert len(helps) == len({l.split()[2] for l in helps})
    assert any(l.startswith('t_trace_spans_total{stage="decode"}')
               for l in lines)
    assert any('stage="decode",replica="1"' in l for l in lines)
    assert any(l.startswith("t_trace_stage_p99_seconds") for l in lines)
    assert any(l.startswith("t_traces_total 1") for l in lines)


def test_speculative_stages_in_trace_stats():
    """The speculate/verify spans a speculative iteration records flow
    through the postmortem stats (obs trace --stats) and the Prometheus
    stage series like any other serving stage."""
    from chainermn_tpu.tools.obs import summarize, to_prometheus

    rows = _synthetic_serve_records()
    st = stage_percentiles(rows)
    assert st["speculate"]["count"] == 1
    assert st["verify"]["p99_s"] == pytest.approx(0.005)
    # the draft proposal and chunked-prefill slices are first-class
    # stages too, parented to the same request root
    assert st["draft"]["count"] == 1
    assert st["prefill_chunk"]["count"] == 1
    chunks = [r for r in rows
              if r.get("name") in ("draft", "prefill_chunk")]
    assert {(r["trace"], r["parent"]) for r in chunks} == \
        {(rows[0]["trace"], rows[0]["parent"])}
    text = to_prometheus(summarize(rows), prefix="t")
    assert 't_trace_spans_total{stage="speculate"} 1' in text
    assert 't_trace_spans_total{stage="verify"} 1' in text
    assert 't_trace_spans_total{stage="draft"} 1' in text
    assert 't_trace_spans_total{stage="prefill_chunk"} 1' in text


# ---------------------------------------------------------------------------
# serving integration (real engines, CPU)
# ---------------------------------------------------------------------------

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    from chainermn_tpu.models.transformer import TransformerLM

    return TransformerLM(vocab=VOCAB, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def lm_params(lm):
    import jax
    import jax.numpy as jnp

    return lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


def make_engine(lm, lm_params, **over):
    from chainermn_tpu.serving import EngineConfig, InferenceEngine

    cfg = dict(block_size=4, n_blocks=64, max_len=64, max_batch=4)
    cfg.update(over)
    return InferenceEngine(lm, lm_params, EngineConfig(**cfg))


def prompts_for(n, rng_seed=7, lo=3, hi=13):
    import numpy as np

    rng = np.random.default_rng(rng_seed)
    return [
        [int(t) for t in rng.integers(0, VOCAB, size=int(l))]
        for l in rng.integers(lo, hi, size=n)
    ]


def _drive(router, prompts, new_tokens=4):
    handles = [router.submit(p, new_tokens) for p in prompts]
    for _ in range(3000):
        router.step()
        if all(h.done for h in handles):
            break
    assert all(h.status == "finished" for h in handles)
    return handles


def test_cluster_disagg_traces_connected(lm, lm_params):
    from chainermn_tpu.serving.cluster import Replica, ReplicaRouter

    tr, _ = make_tracer()
    tracing.install(tr)
    try:
        reps = [
            Replica(0, make_engine(lm, lm_params), role="prefill"),
            Replica(1, make_engine(lm, lm_params), role="decode"),
        ]
        router = ReplicaRouter(reps, prefill_threshold=8)
        # a guaranteed mix: two short prompts decode locally (queue +
        # local prefill spans), two long ones disaggregate (prefill on
        # replica 0, handoff to replica 1)
        prompts = (prompts_for(2, lo=3, hi=6)
                   + prompts_for(2, rng_seed=8, lo=9, hi=13))
        handles = _drive(router, prompts)
    finally:
        tracing.uninstall(tr)
    assert all(h.trace_id for h in handles)
    assert tr.open_count() == 0
    recs = tr.records()
    tr.close()
    trees = stitch(recs)
    assert len(trees) == len(prompts)
    names = set()
    for t in trees.values():
        v = validate_trace(t["spans"])
        assert v["connected"] and not v["orphans"], v
        assert v["monotone"], v
        names |= {s["name"] for s in t["spans"]}
    # short prompts decode locally, long ones disagg through handoff
    assert {"request", "queue", "prefill", "decode", "handoff",
            "deliver", "placement"} <= names
    # every request delivered all its tokens through the deliver span
    delivers = [s for t in trees.values() for s in t["spans"]
                if s["name"] == "deliver"]
    assert all(d["attrs"]["tokens"] == 4 for d in delivers)


def test_tracing_adds_zero_compiles_and_same_streams(lm, lm_params):
    """The zero-overhead contract: identical token streams and IDENTICAL
    compile counts with tracing on vs off — span bookkeeping must never
    reach jit inputs."""
    from chainermn_tpu.serving.cluster import Replica, ReplicaRouter

    prompts = prompts_for(3, rng_seed=11)

    def run(traced):
        tr = None
        if traced:
            tr, _ = make_tracer()
            tracing.install(tr)
        try:
            rep = Replica(0, make_engine(lm, lm_params), role="both")
            router = ReplicaRouter([rep])
            handles = _drive(router, prompts)
        finally:
            if tr is not None:
                tracing.uninstall(tr)
                tr.close()
        st = rep.engine.stats()
        return ([h.tokens for h in handles],
                st["prefill_compiles"], st["decode_compiles"])

    off_streams, off_pc, off_dc = run(traced=False)
    on_streams, on_pc, on_dc = run(traced=True)
    assert on_streams == off_streams
    assert (on_pc, on_dc) == (off_pc, off_dc)
