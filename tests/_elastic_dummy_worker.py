"""Stdlib-only dummy rank for the supervisor unit tests.

No chainermn_tpu / jax imports: the supervisor is pure process
plumbing, and these modes exercise exactly the observable contract —
exit codes, heartbeat-file mtimes, SIGTERM behavior::

    python _elastic_dummy_worker.py <mode>

Modes (rank/incarnation read from CHAINERMN_TPU_ELASTIC_* env):

* ``ok``            — beat a few steps, exit 0.
* ``crash_once``    — exit 3 in incarnation 0, behave like ``ok`` after.
* ``crash_always``  — exit 3 every incarnation (restart-budget tests).
* ``crash_rank1_once`` — rank 1 exits 3 in incarnation 0; everyone
  else loops ``ok``-style (rescale tests).
* ``teardown``      — incarnation 0: rank 1 exits 3 immediately while
  rank 0 IGNORES SIGTERM and beats forever (the supervisor must
  escalate to SIGKILL within its grace window); later incarnations
  ``ok``.
* ``stall``         — incarnation 0: rank 1 stops beating after 2
  beats but stays alive (only the heartbeat deadline can catch it);
  later incarnations ``ok``.
* ``preempt_once``  — incarnation 0: exit 75 (EXIT_PREEMPTED) after 2
  beats; later incarnations ``ok``.
"""

import os
import signal
import sys
import time

EXIT_PREEMPTED = 75


def beat(path, step):
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(str(step))
    os.replace(tmp, path)


def main():
    mode = sys.argv[1]
    rank = int(os.environ.get("CHAINERMN_TPU_ELASTIC_RANK", "0"))
    inc = int(os.environ.get("CHAINERMN_TPU_ELASTIC_INCARNATION", "0"))
    hb = os.environ.get("CHAINERMN_TPU_ELASTIC_HB_FILE")

    first = inc == 0
    if mode == "crash_once" and first:
        print(f"dummy rank {rank}: crashing (inc {inc})", flush=True)
        sys.exit(3)
    if mode == "crash_always":
        sys.exit(3)
    if mode in ("crash_rank1_once", "teardown") and first and rank == 1:
        sys.exit(3)
    if mode == "teardown" and first and rank == 0:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        step = 0
        while True:  # only SIGKILL ends this
            if hb:
                beat(hb, step)
            step += 1
            time.sleep(0.02)

    steps = 4
    for step in range(steps):
        if hb and not (mode == "stall" and first and rank == 1
                       and step >= 2):
            beat(hb, step)
        if mode == "preempt_once" and first and step == 2:
            print(f"dummy rank {rank}: preempted (inc {inc})", flush=True)
            sys.exit(EXIT_PREEMPTED)
        if mode == "stall" and first and rank == 1 and step >= 2:
            time.sleep(60)  # alive but silent; teardown reaps us
        time.sleep(0.05)
    print(f"resumed from iteration {inc * 10}", flush=True)
    print(f"final gstep 4 params_digest {0xabad1dea + rank:08x}",
          flush=True)
    print(f"DUMMY_OK rank={rank} inc={inc}", flush=True)
    sys.exit(0)


if __name__ == "__main__":
    main()
