"""Expert-parallel MoE vs the single-device routing oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import build_mesh
from chainermn_tpu.parallel.moe import dense_moe_oracle, moe_layer, top1_route

# Version-compat wrapper: forwards check_vma under whichever
# replication-check kwarg spelling this jax accepts.
from chainermn_tpu.communicators.base import shard_map_compat as shard_map

E, D, T_PER_DEV = 4, 8, 16


def expert_fn(params, x):
    return jnp.tanh(x @ params["w"]) @ params["w2"]


def make_experts(seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (E, D, 16)) * 0.3,
        "w2": jax.random.normal(k2, (E, 16, D)) * 0.3,
    }


@pytest.fixture(scope="module")
def ep_mesh():
    devs = jax.devices()
    if len(devs) < E:
        pytest.skip("needs 4 devices")
    return build_mesh(inter_size=1, intra_size=E, devices=devs[:E])


def test_top1_route_capacity():
    logits = jnp.array([[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]])
    dispatch, combine = top1_route(logits, 2, capacity=2)
    assert dispatch.shape == (2, 2, 4)
    # Tokens 0,1 fill expert 0's two slots; token 2 dropped (capacity).
    assert dispatch[0, 0, 0] == 1 and dispatch[0, 1, 1] == 1
    assert dispatch[:, :, 2].sum() == 0
    assert dispatch[1, 0, 3] == 1
    # Combine weights are gate probs.
    assert 0 < float(combine[0, 0, 0]) <= 1


@pytest.mark.slow
def test_moe_matches_oracle(ep_mesh):
    experts = make_experts()
    gate_w = jax.random.normal(jax.random.PRNGKey(1), (D, E)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(2), (E * T_PER_DEV, D))

    def body(x, gate_w, experts):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
        return moe_layer(x, gate_w, expert_fn, mine, "intra",
                         capacity_factor=4.0)

    f = jax.jit(
        shard_map(
            body, mesh=ep_mesh,
            in_specs=(P("intra"), P(), P("intra")),
            out_specs=P("intra"),
            check_vma=False,
        )
    )
    out = f(x, gate_w, experts)

    # Oracle must see the same per-device routing: apply it shard-wise
    # (routing/capacity are computed per device by design).
    ref = jnp.concatenate([
        dense_moe_oracle(
            x[i * T_PER_DEV:(i + 1) * T_PER_DEV], gate_w, expert_fn, experts,
            capacity_factor=4.0,
        )
        for i in range(E)
    ])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_moe_gradients_flow(ep_mesh):
    experts = make_experts()
    gate_w = jax.random.normal(jax.random.PRNGKey(1), (D, E)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(2), (E * T_PER_DEV, D))

    def loss(args):
        gate_w, experts = args

        def body(x, gate_w, experts):
            mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
            out = moe_layer(x, gate_w, expert_fn, mine, "intra", 4.0)
            return jnp.sum(out**2)

        f = shard_map(
            body, mesh=ep_mesh,
            in_specs=(P("intra"), P(), P("intra")),
            out_specs=P(),
            check_vma=False,
        )
        return f(x, gate_w, experts)

    g_gate, g_exp = jax.jit(jax.grad(loss))((gate_w, experts))
    assert float(jnp.abs(g_gate).sum()) > 0
    assert all(float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(g_exp))


def test_top2_route_gates_renormalize():
    from chainermn_tpu.parallel.moe import topk_route

    logits = jax.random.normal(jax.random.PRNGKey(0), (8, E))
    dispatch, combine = topk_route(logits, E, capacity=8, k=2)
    # Ample capacity: every token keeps both choices, and its two gate
    # weights renormalize to ~1.
    per_token = np.asarray(combine.sum(axis=(0, 1)))
    np.testing.assert_allclose(per_token, np.ones(8), rtol=1e-5)
    assert float(dispatch.sum()) == 16.0  # 8 tokens x 2 experts


def test_top2_capacity_priority():
    """First choices must claim slots before second choices."""
    from chainermn_tpu.parallel.moe import topk_route

    # Both tokens: first choice expert 0, second choice expert 1.
    logits = jnp.array([[5.0, 4.0, 0.0], [5.0, 4.0, 0.0]])
    dispatch, _ = topk_route(logits, 3, capacity=1, k=2)
    # Expert 0 slot taken by token 0 (first-come); token 1's first choice
    # dropped; expert 1's slot goes to token 0's second choice.
    assert dispatch[0, 0, 0] == 1 and dispatch[0, :, 1].sum() == 0
    assert dispatch[1, 0, 0] == 1


def test_load_balancing_loss_uniform_is_one():
    from chainermn_tpu.parallel.moe import load_balancing_loss

    logits = jnp.zeros((64, E))
    # Uniform probs: aux == E * sum_e (f_e * 1/E) == sum_e f_e == 1.
    np.testing.assert_allclose(
        float(load_balancing_loss(logits, E)), 1.0, rtol=1e-5
    )
    # Collapsed routing (all tokens to expert 0) scores E times worse.
    skew = jnp.full((64, E), -10.0).at[:, 0].set(10.0)
    assert float(load_balancing_loss(skew, E)) > 2.0


def test_moe_layer_top2_matches_oracle(ep_mesh):
    x = jax.random.normal(jax.random.PRNGKey(3), (E * T_PER_DEV, D))
    gate_w = jax.random.normal(jax.random.PRNGKey(4), (D, E)) * 0.5
    experts = make_experts()

    def body(x, gate_w, experts):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
        y, aux = moe_layer(
            x, gate_w, expert_fn, mine, "intra",
            capacity_factor=2.0, k=2, return_aux=True,
        )
        return y, jax.lax.pmean(aux, "intra")

    f = jax.jit(
        shard_map(
            body, mesh=ep_mesh,
            in_specs=(P("intra"), P(), P("intra")),
            out_specs=(P("intra"), P()),
            check_vma=False,
        )
    )
    y, aux = f(x, gate_w, experts)
    assert float(aux["load_balance_loss"]) >= 1.0 - 1e-5
    assert 0.0 <= float(aux["dropped_fraction"]) <= 1.0

    # Distributed routing runs per device shard (T_local tokens, local
    # capacity), the oracle globally — compare shard-wise.
    for e in range(E):
        sl = slice(e * T_PER_DEV, (e + 1) * T_PER_DEV)
        ref_shard = dense_moe_oracle(
            x[sl], gate_w, expert_fn, experts, k=2
        )
        np.testing.assert_allclose(
            np.asarray(y[sl]), np.asarray(ref_shard), rtol=2e-4, atol=2e-4
        )


def test_top1_combine_is_router_probability():
    """k=1 must NOT renormalize: the Switch combine weight is the router
    probability itself (renormalizing pins it to ~1 and starves the router
    of main-loss gradient)."""
    logits = jnp.array([[1.0, 0.0, 0.0, 0.0]])
    probs = jax.nn.softmax(logits, axis=-1)
    _, combine = top1_route(logits, 4, capacity=1)
    np.testing.assert_allclose(
        float(combine.sum()), float(probs[0, 0]), rtol=1e-6
    )


def test_topk_degenerate_mass_drops_choice():
    """A token whose softmax collapses onto one expert must not dispatch a
    spurious second copy (argmax of all-zeros) into expert 0's capacity."""
    from chainermn_tpu.parallel.moe import topk_route

    logits = jnp.array([[200.0, 0.0, 0.0]])  # fp32 softmax: [1, 0, 0]
    dispatch, _ = topk_route(logits, 3, capacity=2, k=2)
    assert float(dispatch.sum()) == 1.0  # only the real first choice


def test_dropped_fraction_metric():
    """Capacity 2 with 3 tokens on one expert: exactly one of four
    (token, choice) routings is dropped -> 1/4."""
    from chainermn_tpu.parallel.moe import topk_route

    logits = jnp.array([[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]])
    dispatch, _ = topk_route(logits, 2, capacity=2, k=1)
    dropped = 1.0 - float(jnp.sum(dispatch)) / (1 * 4)
    np.testing.assert_allclose(dropped, 0.25)


def test_moe_experts_per_device_matches_oracle(ep_mesh):
    """VERDICT r4 item 9: E = 2 x devices — two experts per device run
    under vmap; routing/combine must match the all-local oracle."""
    from chainermn_tpu.parallel.moe import moe_layer as _ml

    epd = 2
    E_big = E * epd
    k1, k2 = jax.random.split(jax.random.PRNGKey(7))
    experts_big = {
        "w": jax.random.normal(k1, (E_big, D, 16)) * 0.3,
        "w2": jax.random.normal(k2, (E_big, 16, D)) * 0.3,
    }
    x = jax.random.normal(jax.random.PRNGKey(8), (E * T_PER_DEV, D))
    gate_w = jax.random.normal(jax.random.PRNGKey(9), (D, E_big)) * 0.5

    def body(x, gate_w, experts):
        # in_spec P("intra") splits the (E_big, ...) leading axis into
        # contiguous chunks of epd — the device-major layout moe_layer
        # requires.
        y, aux = _ml(
            x, gate_w, expert_fn, experts, "intra",
            capacity_factor=2.0, k=1, return_aux=True,
            experts_per_device=epd,
        )
        return y, jax.lax.pmean(aux, "intra")

    f = jax.jit(shard_map(
        body, mesh=ep_mesh,
        in_specs=(P("intra"), P(), P("intra")),
        out_specs=(P("intra"), P()),
        check_vma=False,
    ))
    y, aux = f(x, gate_w, experts_big)
    assert 0.0 <= float(aux["dropped_fraction"]) <= 1.0

    # Shard-wise oracle: each device routes its own T_local tokens over
    # all E_big experts with local capacity.
    for dev in range(E):
        xs = x[dev * T_PER_DEV:(dev + 1) * T_PER_DEV]
        want = dense_moe_oracle(
            xs, gate_w, expert_fn, experts_big, capacity_factor=2.0, k=1
        )
        np.testing.assert_allclose(
            np.asarray(y[dev * T_PER_DEV:(dev + 1) * T_PER_DEV]),
            np.asarray(want), rtol=2e-4, atol=2e-5,
        )


def test_moe_rejects_mismatched_gate_width(ep_mesh):
    x = jnp.ones((E * T_PER_DEV, D))
    gate_w = jnp.ones((D, E + 1))
    experts = make_experts()

    def body(x, gate_w, experts):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
        return moe_layer(x, gate_w, expert_fn, mine, "intra")

    f = shard_map(
        body, mesh=ep_mesh,
        in_specs=(P("intra"), P(), P("intra")), out_specs=P("intra"),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="experts/device"):
        jax.jit(f)(x, gate_w, experts)


def _shard_map_norep(body, **kw):
    """shard_map with replication checking off, across jax versions
    (the kwarg was renamed check_rep -> check_vma)."""
    for flag in ("check_vma", "check_rep"):
        try:
            return shard_map(body, **{**kw, flag: False})
        except TypeError:
            continue
    return shard_map(body, **kw)


def test_return_aux_scalar_shim(ep_mesh):
    """One-release back-compat: ``return_aux='scalar'`` restores the old
    ``(y, load_balance_loss)`` contract (with a DeprecationWarning);
    ``return_aux=True`` now returns ``(y, aux_dict)``."""
    experts = make_experts()
    gate_w = jax.random.normal(jax.random.PRNGKey(11), (D, E)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(12), (E * T_PER_DEV, D))

    def body(mode):
        def inner(x, gate_w, experts):
            mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
            y, aux = moe_layer(
                x, gate_w, expert_fn, mine, "intra", return_aux=mode
            )
            scalar = aux["load_balance_loss"] if mode is True else aux
            return y, jax.lax.pmean(scalar, "intra")

        return inner

    specs = dict(
        mesh=ep_mesh,
        in_specs=(P("intra"), P(), P("intra")),
        out_specs=(P("intra"), P()),
    )
    y_new, lbl_new = jax.jit(_shard_map_norep(body(True), **specs))(
        x, gate_w, experts
    )
    with pytest.warns(DeprecationWarning, match="scalar"):
        y_old, lbl_old = jax.jit(_shard_map_norep(body("scalar"), **specs))(
            x, gate_w, experts
        )
    # The shim's scalar IS the dict's load_balance_loss; y unchanged.
    np.testing.assert_allclose(np.asarray(y_old), np.asarray(y_new),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(float(lbl_old), float(lbl_new), rtol=1e-6)
