"""Expert-parallel MoE vs the single-device routing oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import build_mesh
from chainermn_tpu.parallel.moe import dense_moe_oracle, moe_layer, top1_route

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

E, D, T_PER_DEV = 4, 8, 16


def expert_fn(params, x):
    return jnp.tanh(x @ params["w"]) @ params["w2"]


def make_experts(seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (E, D, 16)) * 0.3,
        "w2": jax.random.normal(k2, (E, 16, D)) * 0.3,
    }


@pytest.fixture(scope="module")
def ep_mesh():
    devs = jax.devices()
    if len(devs) < E:
        pytest.skip("needs 4 devices")
    return build_mesh(inter_size=1, intra_size=E, devices=devs[:E])


def test_top1_route_capacity():
    logits = jnp.array([[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]])
    dispatch, combine = top1_route(logits, 2, capacity=2)
    assert dispatch.shape == (2, 2, 4)
    # Tokens 0,1 fill expert 0's two slots; token 2 dropped (capacity).
    assert dispatch[0, 0, 0] == 1 and dispatch[0, 1, 1] == 1
    assert dispatch[:, :, 2].sum() == 0
    assert dispatch[1, 0, 3] == 1
    # Combine weights are gate probs.
    assert 0 < float(combine[0, 0, 0]) <= 1


def test_moe_matches_oracle(ep_mesh):
    experts = make_experts()
    gate_w = jax.random.normal(jax.random.PRNGKey(1), (D, E)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(2), (E * T_PER_DEV, D))

    def body(x, gate_w, experts):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
        return moe_layer(x, gate_w, expert_fn, mine, "intra",
                         capacity_factor=4.0)

    f = jax.jit(
        shard_map(
            body, mesh=ep_mesh,
            in_specs=(P("intra"), P(), P("intra")),
            out_specs=P("intra"),
            check_vma=False,
        )
    )
    out = f(x, gate_w, experts)

    # Oracle must see the same per-device routing: apply it shard-wise
    # (routing/capacity are computed per device by design).
    ref = jnp.concatenate([
        dense_moe_oracle(
            x[i * T_PER_DEV:(i + 1) * T_PER_DEV], gate_w, expert_fn, experts,
            capacity_factor=4.0,
        )
        for i in range(E)
    ])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_moe_gradients_flow(ep_mesh):
    experts = make_experts()
    gate_w = jax.random.normal(jax.random.PRNGKey(1), (D, E)) * 0.5
    x = jax.random.normal(jax.random.PRNGKey(2), (E * T_PER_DEV, D))

    def loss(args):
        gate_w, experts = args

        def body(x, gate_w, experts):
            mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), experts)
            out = moe_layer(x, gate_w, expert_fn, mine, "intra", 4.0)
            return jnp.sum(out**2)

        f = shard_map(
            body, mesh=ep_mesh,
            in_specs=(P("intra"), P(), P("intra")),
            out_specs=P(),
            check_vma=False,
        )
        return f(x, gate_w, experts)

    g_gate, g_exp = jax.jit(jax.grad(loss))((gate_w, experts))
    assert float(jnp.abs(g_gate).sum()) > 0
    assert all(float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(g_exp))
