"""Host-plane static analysis (H001–H005): seeded fixtures, the
package-wide clean gate with its pinned suppression budget, lock-order
cycle detection, the mirror-before-execute contract against a tampered
engine clone, the wire-schema lockfile, and the CLI.

The two in-tree suppressions are load-bearing and each has a targeted
regression test here: stripping the ``# hostlint: disable`` comment
must re-fire the rule, so a suppression can never outlive the code
pattern it justifies.
"""

import copy
import json
import os
import re
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WIRE_SCHEMAS_PATH = os.path.join(
    REPO_ROOT, "tests", "golden", "wire_schemas.json"
)

#: every in-tree ``# hostlint: disable`` must carry a justifying
#: comment; adding a third suppression means raising this knowingly.
SUPPRESSION_BUDGET = 2


def _analyze_package():
    from chainermn_tpu.analysis import hostlint

    return hostlint.analyze_host(
        hostlint.package_host_files(),
        wire_lock=hostlint.load_wire_lock(WIRE_SCHEMAS_PATH),
    )


def _flagged(report):
    return sorted({f.rule for f in report.findings})


# ----------------------------------------------------------------------
# Seeded fixtures: every H-rule fires on its violating snippet and
# stays silent on the clean twin
# ----------------------------------------------------------------------
@pytest.mark.parametrize("name", ["h001", "h002", "h003", "h004", "h005"])
def test_seeded_host_fixture_flagged(name):
    from chainermn_tpu.analysis import hostlint
    from chainermn_tpu.analysis.fixtures import FIXTURES

    def run(t):
        hf = hostlint.make_host_file(
            t["target"], t["source"],
            wire=t.get("wire", False), det=t.get("det", False),
        )
        return hostlint.analyze_host([hf], wire_lock=t.get("wire_lock"))

    t = FIXTURES[name]()
    report = run(t)
    assert t["expect"] in _flagged(report), report.render()
    for f in report.findings:
        assert f.message and f.fix_hint  # findings must be actionable

    clean = FIXTURES[f"{name}_clean"]()
    report = run(clean)
    assert report.findings == [], report.render()


# ----------------------------------------------------------------------
# Package-wide clean gate + suppression budget
# ----------------------------------------------------------------------
def test_package_hostlint_clean_within_suppression_budget():
    report = _analyze_package()
    assert report.ok, report.render()
    assert set(report.rules_run) == {
        "H001", "H002", "H003", "H004", "H005",
    }
    assert 0 < report.suppressed <= SUPPRESSION_BUDGET, (
        f"{report.suppressed} suppressions vs budget "
        f"{SUPPRESSION_BUDGET} — every '# hostlint: disable' needs a "
        f"justifying comment and a regression test in this file"
    )


def test_wire_lockfile_is_current():
    """The committed lockfile must match what extraction produces from
    the tree — a stale lockfile would let drift through unnoticed."""
    from chainermn_tpu.analysis import hostlint

    current = hostlint.extract_wire_schemas(hostlint.package_host_files())
    stripped = {
        k: {kk: vv for kk, vv in v.items() if kk != "loc"}
        for k, v in current.items()
    }
    with open(WIRE_SCHEMAS_PATH) as fh:
        lock = json.load(fh)
    assert stripped == lock["schemas"], (
        "regenerate with: python -m chainermn_tpu.tools.lint --host "
        "--regen-schemas"
    )
    # the load-bearing structs are actually locked
    for key in ("dataclass:ReplicaLoad", "dataclass:KVSnapshot",
                "cmd:submit", "frame:tok", "meta:kv_snapshot",
                "dataclass:Lease", "dataclass:BeatInfo",
                "cmd:lease_grant", "cmd:lease_yield"):
        assert key in lock["schemas"], key


# ----------------------------------------------------------------------
# H001: lock-order cycle detection
# ----------------------------------------------------------------------
_CYCLE_SRC = '''\
import threading


class Pair:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def forward(self):
        with self.lock_a:
            with self.lock_b:
                pass

    def backward(self):
        with self.lock_b:
            with self.lock_a:
                pass
'''


def test_lock_order_cycle_detected():
    from chainermn_tpu.analysis import hostlint

    report = hostlint.analyze_host([("cycle.py", _CYCLE_SRC)])
    cycles = [f for f in report.findings if "cycle" in f.message]
    assert cycles, report.render()
    assert cycles[0].rule == "H001"
    assert "Pair.lock_a" in cycles[0].message
    assert "Pair.lock_b" in cycles[0].message

    # one consistent order: no cycle
    consistent = _CYCLE_SRC.replace(
        "        with self.lock_b:\n            with self.lock_a:",
        "        with self.lock_a:\n            with self.lock_b:",
    )
    report = hostlint.analyze_host([("ordered.py", consistent)])
    assert not [f for f in report.findings if "cycle" in f.message]


# ----------------------------------------------------------------------
# H003: negative test against a tampered clone of the REAL engine
# ----------------------------------------------------------------------
def _engine_source():
    path = os.path.join(
        REPO_ROOT, "chainermn_tpu", "serving", "engine.py"
    )
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def test_h003_fires_on_mirror_stripped_engine_clone():
    """Delete the decode path's mirror emit from a clone of the real
    engine source: H003 must catch the regression the shard-group soak
    used to be the only guard against."""
    from chainermn_tpu.analysis import hostlint

    src = _engine_source()
    lines = src.splitlines(keepends=True)
    stripped = [
        ln for ln in lines
        if not re.search(r'self\._mirror\(\s*"decode"', ln)
    ]
    assert len(stripped) < len(lines), "decode mirror emit not found"
    report = hostlint.analyze_host([("engine_clone.py", "".join(stripped))])
    hits = [f for f in report.findings if f.rule == "H003"]
    assert any("_decode_step" in f.message for f in hits), report.render()


def test_h003_suppression_in_apply_plan_is_load_bearing():
    """_apply_plan's cache re-placement carries a justified suppression;
    stripping the comment must re-fire H003 (and today's tree must need
    exactly that one suppression in the engine)."""
    from chainermn_tpu.analysis import hostlint

    src = _engine_source()
    assert "# hostlint: disable=H003" in src
    bare = src.replace("  # hostlint: disable=H003", "")
    report = hostlint.analyze_host([("engine_bare.py", bare)])
    hits = [f for f in report.findings if f.rule == "H003"]
    assert any("_apply_plan" in f.message for f in hits), report.render()
    assert len(hits) == 1, report.render()


# ----------------------------------------------------------------------
# H001 suppression regression: rep.alive thread-confinement contract
# ----------------------------------------------------------------------
def _router_source():
    path = os.path.join(
        REPO_ROOT, "chainermn_tpu", "serving", "cluster", "router.py"
    )
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def test_h001_alive_suppression_is_load_bearing():
    from chainermn_tpu.analysis import hostlint

    src = _router_source()
    assert "# hostlint: disable=H001" in src
    bare = src.replace("  # hostlint: disable=H001", "")
    report = hostlint.analyze_host([("router_bare.py", bare)])
    hits = [f for f in report.findings if f.rule == "H001"]
    assert any("rep.alive" in f.message for f in hits), report.render()


def test_alive_flag_is_one_way_in_router():
    """The suppression's justification: ``alive`` may only ever be
    written False by the router, so bare reads race benignly.  Anyone
    resurrecting a replica in place invalidates the argument and must
    revisit the locking."""
    assert not re.search(r"\.alive\s*=\s*True", _router_source())


# ----------------------------------------------------------------------
# H004: tamper goldens — reorder and default-less append must fail
# ----------------------------------------------------------------------
def _current_and_lock():
    from chainermn_tpu.analysis import hostlint

    current = hostlint.extract_wire_schemas(hostlint.package_host_files())
    with open(WIRE_SCHEMAS_PATH) as fh:
        lock = json.load(fh)
    return current, lock


def test_h004_field_reorder_fails():
    from chainermn_tpu.analysis import hostlint

    current, lock = _current_and_lock()
    tampered = copy.deepcopy(current)
    fields = tampered["dataclass:ReplicaLoad"]["fields"]
    fields[0], fields[1] = fields[1], fields[0]
    findings = hostlint.compare_wire_schemas(tampered, lock)
    assert any(
        f.severity == "error" and "reordered" in f.message
        and "ReplicaLoad" in f.message for f in findings
    ), [f.render() for f in findings]


def test_h004_defaultless_append_fails():
    from chainermn_tpu.analysis import hostlint

    current, lock = _current_and_lock()
    tampered = copy.deepcopy(current)
    tampered["dataclass:ReplicaLoad"]["fields"].append(["bogus", False])
    findings = hostlint.compare_wire_schemas(tampered, lock)
    assert any(
        f.severity == "error" and "no default" in f.message
        for f in findings
    ), [f.render() for f in findings]


def test_h004_defaulted_append_and_new_struct_pass():
    """The sanctioned evolutions: a defaulted trailing field is silent;
    a brand-new struct warns (bless via --regen-schemas) but does not
    fail the gate."""
    from chainermn_tpu.analysis import hostlint

    current, lock = _current_and_lock()
    grown = copy.deepcopy(current)
    grown["dataclass:ReplicaLoad"]["fields"].append(["extra", True])
    grown["cmd:brand_new"] = {"keys": ["op"], "loc": ("x.py", 1)}
    findings = hostlint.compare_wire_schemas(grown, lock)
    assert not [f for f in findings if f.severity == "error"], (
        [f.render() for f in findings]
    )
    assert any("brand_new" in f.message for f in findings)


def test_h004_struct_removal_fails():
    from chainermn_tpu.analysis import hostlint

    current, lock = _current_and_lock()
    tampered = copy.deepcopy(current)
    del tampered["frame:tok"]
    findings = hostlint.compare_wire_schemas(tampered, lock)
    assert any(
        f.severity == "error" and "frame:tok" in f.message
        for f in findings
    )


def test_regen_schemas_flow(tmp_path, monkeypatch):
    """--host --regen-schemas rewrites the lockfile from the tree and
    the result diffs clean against a fresh extraction."""
    from chainermn_tpu.analysis import hostlint
    from chainermn_tpu.tools import lint as lint_cli

    target = tmp_path / "wire_schemas.json"
    monkeypatch.setattr(
        lint_cli, "_wire_schemas_path", lambda: str(target)
    )
    assert lint_cli.main(["--host", "--regen-schemas"]) == 0
    regenerated = json.loads(target.read_text())
    current = hostlint.extract_wire_schemas(hostlint.package_host_files())
    findings = hostlint.compare_wire_schemas(current, regenerated)
    assert findings == [], [f.render() for f in findings]


# ----------------------------------------------------------------------
# Suppression surfaces shared with the R-rules
# ----------------------------------------------------------------------
def test_line_scoped_suppression_counts():
    from chainermn_tpu.analysis import hostlint
    from chainermn_tpu.analysis.fixtures import _H001_BAD

    suppressed_src = _H001_BAD.replace(
        "        self.value = 0\n",
        "        # single-threaded teardown path\n"
        "        self.value = 0  # hostlint: disable=H001\n",
    )
    report = hostlint.analyze_host([("s.py", suppressed_src)])
    assert report.ok and report.suppressed == 1


def test_env_disable_applies_to_host_rules(monkeypatch):
    from chainermn_tpu.analysis import ENV_DISABLE, hostlint
    from chainermn_tpu.analysis.fixtures import _H001_BAD

    monkeypatch.setenv(ENV_DISABLE, "H001")
    report = hostlint.analyze_host([("s.py", _H001_BAD)])
    assert report.ok and report.suppressed == 1


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------
def test_cli_host_in_process(capsys):
    from chainermn_tpu.tools import lint as lint_cli

    rc = lint_cli.main(["--host", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True
    (host,) = [t for t in payload["targets"] if t["target"] == "host"]
    assert host["suppressed"] == SUPPRESSION_BUDGET
    assert host["rules_run"] == ["H001", "H002", "H003", "H004", "H005"]


def test_cli_host_subprocess_smoke():
    from conftest import subprocess_env

    proc = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.lint",
         "--host", "--format", "json"],
        capture_output=True, text=True, timeout=240,
        env=subprocess_env(),
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert [t["target"] for t in payload["targets"]] == ["host"]


def test_cli_host_fixture_exits_nonzero(capsys):
    from chainermn_tpu.tools import lint as lint_cli

    rc = lint_cli.main(["--fixtures", "h003", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["ok"] is False
    assert {f["rule"] for t in payload["targets"]
            for f in t["findings"]} == {"H003"}
