"""Worker for the on-TPU test tier (run as a subprocess with the DEFAULT
environment, i.e. the axon/TPU plugin active — unlike every other worker,
which scrubs it).

Subcommands:
  probe      — print the default backend name and exit
  flash      — compiled (non-interpret) flash attention fwd+bwd vs the XLA
               oracle ON THE CHIP; asserts and prints OK
  trainstep  — 3 data-parallel train steps on whatever backend is active;
               prints per-step losses (the pytest side runs this twice,
               chip vs CPU, and compares)

The reference gated GPU tests with ``@attr.gpu`` markers (SURVEY §4); this
is that tier for TPU — the compiled kernel path is correctness-asserted on
the real chip, not just timed by bench.py.
"""

import sys

import jax

from chainermn_tpu.utils.profiling import setup_compilation_cache

setup_compilation_cache()

import jax.numpy as jnp
import numpy as np


def probe():
    print(jax.default_backend())


def _assert_grads_close(g, gref, tol, ctx):
    """Per-component max relative error: grad magnitudes vary over orders
    of magnitude, so compare at the scale of the reference gradient."""
    for a, b, name in zip(g, gref, "qkv"):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        assert a32.shape == b32.shape, (name, ctx, a32.shape, b32.shape)
        denom = max(1e-6, float(np.abs(b32).max()))
        err = float(np.abs(a32 - b32).max()) / denom
        assert err < tol, (name, ctx, err)


def flash():
    from chainermn_tpu.ops.flash_attention import _xla_attention, flash_attention

    assert jax.default_backend() in ("tpu", "axon"), jax.default_backend()
    rng = np.random.RandomState(0)
    for dtype, causal, S, tol in [
        (jnp.bfloat16, True, 1024, 2e-2),
        (jnp.bfloat16, False, 1024, 2e-2),
        (jnp.float32, True, 1024, 2e-3),
    ]:
        B, H, D = 1, 2, 64
        q, k, v = (
            jnp.asarray(rng.randn(B, S, H, D), dtype) / (D**0.25)
            for _ in range(3)
        )

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=False)
            return (o.astype(jnp.float32) ** 2).sum()

        def loss_xla(q, k, v):
            o = _xla_attention(q, k, v, 1.0 / D**0.5, causal)
            return (o.astype(jnp.float32) ** 2).sum()

        o = jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, interpret=False
            )
        )(q, k, v)
        ref = jax.jit(
            lambda q, k, v: _xla_attention(q, k, v, 1.0 / D**0.5, causal)
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o, np.float32), np.asarray(ref, np.float32),
            rtol=tol, atol=tol,
        )

        g = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gref = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))(q, k, v)
        _assert_grads_close(g, gref, 10 * tol, (dtype, causal))
        print(f"flash-on-tpu ok: dtype={jnp.dtype(dtype).name} causal={causal}")

    # Segment-id masks (packed sequences), compiled: fwd + grads match the
    # dense oracle; padding rows are exactly zero in BOTH passes.
    B, S, H, D = 2, 1024, 2, 128
    q, k, v = (
        jnp.asarray(rng.randn(B, S, H, D) * 0.3, jnp.bfloat16)
        for _ in range(3)
    )
    seg = np.zeros((B, S), np.int32)
    seg[:, 400:800] = 1
    seg[:, 800:] = -1
    kv_seg = seg.copy()
    kv_seg[kv_seg == -1] = -2
    qs, ks = jnp.asarray(seg), jnp.asarray(kv_seg)
    o = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, q_segment_ids=qs, kv_segment_ids=ks
    ))(q, k, v)
    ref = _xla_attention(
        q, k, v, 1.0 / D**0.5, True, q_segment_ids=qs, kv_segment_ids=ks
    )
    np.testing.assert_allclose(
        np.asarray(o, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )
    assert np.all(np.asarray(o)[:, 800:] == 0)
    g = jax.jit(jax.grad(lambda q: jnp.sum(jnp.sin(flash_attention(
        q, k, v, causal=True, q_segment_ids=qs, kv_segment_ids=ks
    ).astype(jnp.float32)))))(q)
    gx = jax.grad(lambda q: jnp.sum(jnp.sin(_xla_attention(
        q, k, v, 1.0 / D**0.5, True, q_segment_ids=qs, kv_segment_ids=ks
    ).astype(jnp.float32))))(q)
    np.testing.assert_allclose(
        np.asarray(g, np.float32), np.asarray(gx, np.float32),
        rtol=2e-2, atol=2e-2,
    )
    assert np.all(np.asarray(g)[:, 800:] == 0)
    print("flash-on-tpu ok: segmented")

    # Wide heads (Mosaic-padded lane tiles), compiled: one non-multiple
    # of 128 and the 256 ceiling.
    for D2 in (160, 256):
        q2, k2, v2 = (
            jnp.asarray(rng.randn(1, 512, 2, D2) * 0.2, jnp.bfloat16)
            for _ in range(3)
        )
        o2 = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
            q2, k2, v2
        )
        w2 = _xla_attention(q2, k2, v2, 1.0 / D2**0.5, True)
        np.testing.assert_allclose(
            np.asarray(o2, np.float32), np.asarray(w2, np.float32),
            rtol=2e-2, atol=2e-2,
        )
        print(f"flash-on-tpu ok: D={D2}")

    # GQA / MQA, COMPILED (the b // G index maps and the widened dkv
    # grid have Mosaic lowerings of their own — interpret-mode coverage
    # alone would not pin them): fwd + all three grads vs the
    # broadcast-kv oracle, for a 2-group and an MQA head layout.
    for Hk in (2, 1):
        B3, S3, H3, D3 = 2, 1024, 4, 128
        q3 = jnp.asarray(rng.randn(B3, S3, H3, D3) * 0.3, jnp.bfloat16)
        k3 = jnp.asarray(rng.randn(B3, S3, Hk, D3) * 0.3, jnp.bfloat16)
        v3 = jnp.asarray(rng.randn(B3, S3, Hk, D3) * 0.3, jnp.bfloat16)
        G = H3 // Hk

        def gqa_ref(q, k, v):
            return _xla_attention(
                q, jnp.repeat(k, G, axis=2), jnp.repeat(v, G, axis=2),
                1.0 / D3**0.5, True,
            )

        o3 = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
            q3, k3, v3
        )
        np.testing.assert_allclose(
            np.asarray(o3, np.float32),
            np.asarray(gqa_ref(q3, k3, v3), np.float32),
            rtol=2e-2, atol=2e-2,
        )
        g3 = jax.jit(jax.grad(
            lambda q, k, v: (flash_attention(
                q, k, v, causal=True
            ).astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2),
        ))(q3, k3, v3)
        gr3 = jax.jit(jax.grad(
            lambda q, k, v: (gqa_ref(q, k, v).astype(jnp.float32) ** 2)
            .sum(),
            argnums=(0, 1, 2),
        ))(q3, k3, v3)
        _assert_grads_close(g3, gr3, 0.2, ("gqa", Hk))
        print(f"flash-on-tpu ok: GQA Hk={Hk}")

    # Sliding-window band, COMPILED: the band mask and the two-sided
    # block skips have their own Mosaic lowering; fwd + grads vs the
    # dense banded oracle at a window spanning ~1.5 blocks.
    Bw, Sw, Hw, Dw, W = 1, 1024, 2, 128, 200
    qw, kw, vw = (
        jnp.asarray(rng.randn(Bw, Sw, Hw, Dw) * 0.3, jnp.bfloat16)
        for _ in range(3)
    )

    def banded_ref(q, k, v):
        # _xla_attention's band path is itself pinned against an
        # independent hand-rolled oracle in tests/test_flash_attention.py.
        return _xla_attention(q, k, v, 1.0 / Dw**0.5, True, window=W)

    ow = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, window=W
    ))(qw, kw, vw)
    np.testing.assert_allclose(
        np.asarray(ow, np.float32),
        np.asarray(banded_ref(qw, kw, vw), np.float32),
        rtol=2e-2, atol=2e-2,
    )
    gw = jax.jit(jax.grad(
        lambda q, k, v: (flash_attention(
            q, k, v, causal=True, window=W
        ).astype(jnp.float32) ** 2).sum(),
        argnums=(0, 1, 2),
    ))(qw, kw, vw)
    gwr = jax.jit(jax.grad(
        lambda q, k, v: (banded_ref(q, k, v).astype(jnp.float32) ** 2)
        .sum(),
        argnums=(0, 1, 2),
    ))(qw, kw, vw)
    _assert_grads_close(gw, gwr, 0.2, ("window", W))
    print(f"flash-on-tpu ok: window W={W}")
    print("OK")


def trainstep():
    import optax

    import chainermn_tpu
    from chainermn_tpu.communicators import create_communicator

    # TPU's DEFAULT f32 matmul precision uses bf16 MXU passes (~1e-3 off
    # a CPU fp32 run); force true fp32 so chip-vs-CPU trajectories are
    # comparable at tight tolerance.
    jax.config.update("jax_default_matmul_precision", "highest")
    comm = create_communicator("xla_ici")
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(16, 4), jnp.float32) * 0.1
    params = {"w": W}

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.sgd(0.1)
    mopt = chainermn_tpu.create_multi_node_optimizer(opt, comm)
    state = mopt.init(params)
    step = mopt.make_train_step(loss_fn)

    # Fixed global batch so the chip run (whatever the pool's device
    # count) and the 1-device CPU run draw identical data; DP averaging
    # makes the trajectory device-count-invariant as long as 16 divides
    # the device count's shard arithmetic.
    n = 16
    for i in range(3):
        x = jnp.asarray(rng.randn(n, 16), jnp.float32)
        y = jnp.asarray(rng.randn(n, 4), jnp.float32)
        batch = comm.global_batch((x, y))
        params, state, loss = step(params, state, batch)
        print(f"loss {i}: {float(loss):.8f}")


if __name__ == "__main__":
    cmd = sys.argv[1]
    {"probe": probe, "flash": flash, "trainstep": trainstep}[cmd]()
