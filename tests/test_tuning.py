"""Kernel-autotuning subsystem: cache round-trips, search-space validity,
the pytest/off-TPU determinism guards, miss -> static-default fallback,
numerics parity of searched configs, and the CLI's --dry-run mode.

Everything here runs on the CPU harness — by design the tuner must be
INERT in this context (no timing, no cache reads in the ops, no files
written into the repo), and these tests pin that contract.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.tuning import (
    DEFAULT_CACHE_PATH,
    ENV_CACHE_PATH,
    TuneCache,
    autotune_enabled,
    bucket_pow2,
    runtime_lookup_enabled,
)
from chainermn_tpu.tuning import autotune as autotune_mod
from chainermn_tpu.tuning.cache import CACHE_VERSION, make_key
from chainermn_tpu.tuning.search_space import (
    ce_search_space,
    flash_cache_key,
    flash_default_config,
    flash_search_space,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Cache mechanics.
# ---------------------------------------------------------------------------


def test_cache_round_trip(tmp_path):
    path = str(tmp_path / "tune.json")
    c = TuneCache(path)
    key = make_key("flash_fwd", "TPU v5e", "bfloat16",
                   (("q", 4096), ("k", 4096), ("d", 128)),
                   {"causal": True, "window": 0})
    c.put(key, {"block_q": 256, "block_k": 512, "seconds": 1.5e-3})
    c.save()

    reread = TuneCache(path).get(key)
    assert reread is not None
    assert reread["block_q"] == 256 and reread["block_k"] == 512
    # The file itself is versioned JSON.
    with open(path) as f:
        data = json.load(f)
    assert data["version"] == CACHE_VERSION and key in data["entries"]


def test_cache_corrupt_file_degrades_to_empty(tmp_path):
    path = str(tmp_path / "tune.json")
    with open(path, "w") as f:
        f.write("{not json")
    c = TuneCache(path)
    assert c.get("anything") is None and len(c) == 0
    # Wrong version: also a miss everywhere, not an error.
    with open(path, "w") as f:
        json.dump({"version": CACHE_VERSION + 999,
                   "entries": {"k": {"block_q": 1}}}, f)
    assert TuneCache(path).get("k") is None
    # Missing file: same.
    assert TuneCache(str(tmp_path / "absent.json")).get("k") is None


def test_cache_save_is_atomic_no_temp_left(tmp_path):
    path = str(tmp_path / "sub" / "tune.json")
    c = TuneCache(path)
    c.put("k", {"chunk": 256})
    c.save()
    assert TuneCache(path).get("k") == {"chunk": 256}
    leftovers = [f for f in os.listdir(tmp_path / "sub")
                 if f != "tune.json"]
    assert leftovers == []


def test_bucket_pow2():
    assert bucket_pow2(1) == 1
    assert bucket_pow2(2) == 2
    assert bucket_pow2(3) == 4
    assert bucket_pow2(4096) == 4096
    assert bucket_pow2(4097) == 8192
    assert bucket_pow2(3072) == 4096


def test_make_key_deterministic_flag_order():
    a = make_key("k", "dev", "bfloat16", (("q", 8),),
                 {"b": True, "a": 0})
    b = make_key("k", "dev", "bfloat16", (("q", 8),),
                 {"a": 0, "b": True})
    assert a == b and "b=1" in a


# ---------------------------------------------------------------------------
# Determinism guards: under pytest the whole subsystem is inert.
# ---------------------------------------------------------------------------


def test_tuner_is_inert_under_pytest():
    assert not autotune_enabled()
    assert not runtime_lookup_enabled()
    # Runtime lookups short-circuit to None before touching any file.
    assert autotune_mod.lookup_flash_blocks(
        "fwd", Sq=4096, Sk=4096, D=128, dtype="bfloat16", causal=True
    ) is None
    assert autotune_mod.lookup_ce_chunk(
        N=4096, V=32768, D=2048, dtype="bfloat16"
    ) is None
    # And the measurement harness refuses outright.
    with pytest.raises(RuntimeError, match="disabled"):
        autotune_mod.tune_fused_ce(N=256, V=64, D=32)


def test_default_cache_path_outside_repo():
    assert DEFAULT_CACHE_PATH.startswith("/tmp/")
    assert not os.path.abspath(DEFAULT_CACHE_PATH).startswith(REPO_ROOT)


def test_env_disable_wins(monkeypatch):
    monkeypatch.setenv("CHAINERMN_TPU_AUTOTUNE", "0")
    assert not autotune_enabled()


# ---------------------------------------------------------------------------
# Runtime lookup validation (simulating the on-TPU path).
# ---------------------------------------------------------------------------


def _enable_lookups(monkeypatch, tmp_path):
    """Point the shared cache at a tmp file and force the backend gate
    open — the only way to exercise the lookup path on the CPU harness."""
    monkeypatch.setenv(ENV_CACHE_PATH, str(tmp_path / "tune.json"))
    monkeypatch.setattr(autotune_mod, "runtime_lookup_enabled", lambda: True)


def test_lookup_returns_tuned_blocks(monkeypatch, tmp_path):
    _enable_lookups(monkeypatch, tmp_path)
    from chainermn_tpu.tuning.cache import device_kind, shared_cache

    key = flash_cache_key("fwd", device_kind(), "float32",
                          512, 512, 64, True, None)
    c = TuneCache(str(tmp_path / "tune.json"))
    c.put(key, {"block_q": 128, "block_k": 64})
    c.save()
    assert shared_cache().get(key) is not None
    got = autotune_mod.lookup_flash_blocks(
        "fwd", Sq=512, Sk=512, D=64, dtype="float32", causal=True
    )
    assert got == (128, 64)


def test_lookup_rejects_entry_invalid_for_actual_shape(monkeypatch, tmp_path):
    """pow2 bucketing means S=384 hits the 512 bucket; an entry whose
    blocks do not divide 384 must be ignored, not crash the kernel."""
    _enable_lookups(monkeypatch, tmp_path)
    from chainermn_tpu.tuning.cache import device_kind

    key = flash_cache_key("fwd", device_kind(), "float32",
                          384, 384, 64, True, None)
    c = TuneCache(str(tmp_path / "tune.json"))
    c.put(key, {"block_q": 512, "block_k": 512})
    c.save()
    assert autotune_mod.lookup_flash_blocks(
        "fwd", Sq=384, Sk=384, D=64, dtype="float32", causal=True
    ) is None


def test_lookup_miss_is_none(monkeypatch, tmp_path):
    _enable_lookups(monkeypatch, tmp_path)
    assert autotune_mod.lookup_ce_chunk(
        N=1024, V=999, D=7, dtype="float32"
    ) is None


# ---------------------------------------------------------------------------
# Search spaces.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype,sub", [("bfloat16", 16), ("float32", 8)])
def test_flash_search_space_validity(dtype, sub):
    Sq = Sk = 2048
    space = flash_search_space(Sq, Sk, 128, dtype, which="fwd")
    assert space
    for cfg in space:
        assert Sq % cfg["block_q"] == 0 and Sk % cfg["block_k"] == 0
        assert cfg["block_q"] % sub == 0 and cfg["block_k"] % sub == 0
    assert flash_default_config(Sq, Sk) in space
    # The VMEM model prunes: a giant head dim shrinks the space.
    big_d = flash_search_space(Sq, Sk, 2048, dtype, which="fwd")
    assert len(big_d) < len(space)


def test_flash_bwd_space_tighter_than_fwd():
    fwd = flash_search_space(4096, 4096, 128, "bfloat16", which="fwd")
    bwd = flash_search_space(4096, 4096, 128, "bfloat16", which="bwd")
    assert bwd and len(bwd) <= len(fwd)


def test_ce_search_space_divisors_and_default():
    from chainermn_tpu.ops.fused_ce import DEFAULT_CHUNK, _pick_chunk

    N = 16384
    space = ce_search_space(N, 32768, 2048)
    assert space and all(N % c["chunk"] == 0 for c in space)
    assert {"chunk": _pick_chunk(N, DEFAULT_CHUNK)} in space
    # Non-pow2 row count: the default _pick_chunk divisor still appears.
    odd = ce_search_space(96, 64, 32)
    assert {"chunk": _pick_chunk(96, DEFAULT_CHUNK)} in odd


# ---------------------------------------------------------------------------
# Op fallback + parity: a miss (or any off-TPU call) is the static default.
# ---------------------------------------------------------------------------


def test_fused_ce_chunk_none_is_static_default():
    from chainermn_tpu.ops.fused_ce import DEFAULT_CHUNK, fused_cross_entropy

    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(96, 32).astype(np.float32))
    e = jnp.asarray(rng.randn(50, 32).astype(np.float32) * 0.1)
    lab = jnp.asarray(rng.randint(0, 50, size=96), jnp.int32)
    got = fused_cross_entropy(h, e, lab)  # chunk=None -> tuned-or-default
    want = fused_cross_entropy(h, e, lab, chunk=DEFAULT_CHUNK)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_ce_rejects_bad_chunk():
    from chainermn_tpu.ops.fused_ce import fused_cross_entropy

    h = jnp.zeros((8, 4))
    e = jnp.zeros((6, 4))
    lab = jnp.zeros((8,), jnp.int32)
    with pytest.raises(ValueError):
        fused_cross_entropy(h, e, lab, chunk=0)


def test_flash_default_blocks_match_explicit():
    """block_q=block_k=None off-TPU must be EXACTLY the static auto
    geometry — no cache consulted, bit-identical output."""
    from chainermn_tpu.ops.flash_attention import (
        auto_block_size,
        flash_attention,
    )

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 256, 2, 64), jnp.float32)
               for kk in ks)
    b = auto_block_size(256)
    out_auto = flash_attention(q, k, v, causal=True)
    out_pinned = flash_attention(q, k, v, causal=True, block_q=b, block_k=b)
    np.testing.assert_array_equal(np.asarray(out_auto),
                                  np.asarray(out_pinned))


def test_flash_candidate_configs_numerically_match_default():
    """Every searched geometry computes the same attention (the tuner
    only ever changes speed, never values)."""
    from chainermn_tpu.ops.flash_attention import flash_attention

    S, D = 256, 64
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (1, S, 2, D), jnp.float32)
               for kk in ks)
    ref = flash_attention(q, k, v, causal=True)
    for cfg in flash_search_space(S, S, D, "float32", which="fwd"):
        out = flash_attention(
            q, k, v, causal=True,
            block_q=cfg["block_q"], block_k=cfg["block_k"],
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"config {cfg} diverged",
        )


def test_flash_bwd_blocks_numerics_match():
    """A tuned backward geometry different from the forward's must give
    the same gradients."""
    from chainermn_tpu.ops.flash_attention import flash_attention

    S, D = 128, 64
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q, k, v = (jax.random.normal(kk, (1, S, 2, D), jnp.float32)
               for kk in ks)

    def loss(q, k, v, **kw):
        return jnp.sum(flash_attention(q, k, v, causal=True, **kw) ** 2)

    g_ref = jax.grad(loss, argnums=(0, 1, 2))(
        q, k, v, block_q=64, block_k=64)
    g_tuned = jax.grad(loss, argnums=(0, 1, 2))(
        q, k, v, block_q=64, block_k=64, block_q_bwd=32, block_k_bwd=32)
    for a, b in zip(g_ref, g_tuned):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# Dry-run enumeration + CLI.
# ---------------------------------------------------------------------------


def test_serve_group_search_space_validity_and_default():
    from chainermn_tpu.tuning import serve_group_search_space

    space = serve_group_search_space(8, 4096, 1024, n_devices=4,
                                     max_batch=4)
    assert space[0] == {"group_size": 1, "pp_stages": 1}  # static default
    assert {"group_size": 4, "pp_stages": 4} in space
    for cfg in space:
        assert cfg["group_size"] <= 4 and 8 % cfg["group_size"] == 0
        assert cfg["pp_stages"] <= 4
    # geometry gates: odd head count kills K=2/4; device count caps K
    assert all(c["group_size"] == 1 for c in
               serve_group_search_space(3, 4096, 1024, 8, 4))
    assert all(c["group_size"] <= 2 for c in
               serve_group_search_space(8, 4096, 1024, 2, 4))
    # batch of 1 leaves no microbatches to pipeline
    assert all(c["pp_stages"] == 1 for c in
               serve_group_search_space(8, 4096, 1024, 4, 1))


def test_tune_serve_group_dry_run_enumerates_without_timing(tmp_path,
                                                            monkeypatch):
    from chainermn_tpu.tuning import tune_serve_group

    cache_file = tmp_path / "tune.json"
    monkeypatch.setenv(ENV_CACHE_PATH, str(cache_file))
    out = tune_serve_group(dry_run=True)
    assert out["dry_run"] and out["kernel"] == "serve_group"
    assert out["default"] == {"group_size": 1, "pp_stages": 1}
    assert out["default"] in out["candidates"]
    assert not cache_file.exists()


def test_tune_lm_shapes_dry_run_times_nothing(tmp_path, monkeypatch):
    """dry_run enumerates the spaces with no compilation, no timing and
    no cache writes — and is allowed even where tuning is disabled."""
    from chainermn_tpu.tuning import tune_lm_shapes

    cache_file = tmp_path / "tune.json"
    monkeypatch.setenv(ENV_CACHE_PATH, str(cache_file))
    out = tune_lm_shapes(
        batch=2, seq=1024, n_heads=4, d_model=256, vocab=512,
        dry_run=True,
    )
    assert out["flash"]["dry_run"] and out["fused_ce"]["dry_run"]
    assert out["flash"]["fwd"]["candidates"]
    assert out["flash"]["bwd"]["candidates"]
    assert out["fused_ce"]["candidates"]
    assert not cache_file.exists()


def test_autotune_cli_dry_run_smoke(tmp_path):
    """The shipped CLI must enumerate without a TPU and without writing
    anything (the CI determinism guard for the tool itself)."""
    from conftest import subprocess_env

    env = subprocess_env()
    env[ENV_CACHE_PATH] = str(tmp_path / "cli_tune.json")
    proc = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.autotune",
         "--dry-run", "--quiet",
         "--batch", "1", "--seq", "512", "--heads", "2",
         "--d-model", "128", "--vocab", "256"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    kernels = set()
    for rec in lines:
        kernels.update(rec)
    assert kernels == {"flash", "fused_ce"}
    assert not (tmp_path / "cli_tune.json").exists()


def test_autotune_cli_refuses_cpu_timing():
    """Asked to actually TIME kernels on a CPU backend, the CLI must bail
    (exit 2) rather than persist meaningless configs."""
    from conftest import subprocess_env

    proc = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.autotune", "--quiet"],
        capture_output=True, text=True, timeout=240,
        env=subprocess_env(), cwd=REPO_ROOT,
    )
    assert proc.returncode == 2, (proc.stdout, proc.stderr[-2000:])
    assert "error" in json.loads(proc.stdout.splitlines()[-1])
