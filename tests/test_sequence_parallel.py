"""Ring attention + Ulysses tests: sequence-parallel outputs must match the
single-device full-attention oracle, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import build_mesh
from chainermn_tpu.parallel.ring_attention import ring_attention
from chainermn_tpu.parallel.ulysses import ulysses_attention

# Version-compat wrapper: forwards check_vma under whichever
# replication-check kwarg spelling this jax accepts.
from chainermn_tpu.communicators.base import shard_map_compat as shard_map


def full_attention(q, k, v, causal=True):
    B, S, H, D = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D**0.5)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    w = jax.nn.softmax(logits)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def make_qkv(B=2, S=16, H=4, D=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def seq_mesh(request):
    import jax as _jax

    devs = _jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    return build_mesh(inter_size=1, intra_size=4, devices=devs[:4])


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(seq_mesh, causal):
    q, k, v = make_qkv()

    def body(q, k, v):
        return ring_attention(q, k, v, "intra", causal=causal)

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(seq_mesh, causal):
    q, k, v = make_qkv()

    def body(q, k, v):
        return ulysses_attention(q, k, v, "intra", causal=causal)

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(q, k, v)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_gradients_match(seq_mesh):
    q, k, v = make_qkv()

    def dist_loss(qkv):
        q, k, v = qkv

        def body(q, k, v):
            return ring_attention(q, k, v, "intra", causal=True)

        f = shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(qkv):
        q, k, v = qkv
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    g_dist = jax.jit(jax.grad(dist_loss))((q, k, v))
    g_ref = jax.grad(ref_loss)((q, k, v))
    for gd, gr in zip(g_dist, g_ref):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_bad_head_count(seq_mesh):
    q, k, v = make_qkv(H=3)

    def body(q, k, v):
        return ulysses_attention(q, k, v, "intra")

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            shard_map(
                body, mesh=seq_mesh,
                in_specs=(P(None, "intra"),) * 3,
                out_specs=P(None, "intra"),
                check_vma=False,
            )
        )(q, k, v)


@pytest.mark.slow
def test_sequence_parallel_transformer_lm_matches_dense(seq_mesh):
    """FULL sequence-parallel LM: tokens sharded over the sequence axis,
    ring attention + global position offsets — output must match the dense
    single-device model exactly."""
    import jax.lax as lax

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.parallel.ring_attention import make_ring_attention_fn

    vocab, S, n_sp = 32, 16, 4
    dense = TransformerLM(
        vocab=vocab, d_model=16, n_heads=4, d_ff=32, n_layers=2,
        max_len=S, dtype=jnp.float32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, S), 0, vocab)
    params = dense.init(jax.random.PRNGKey(1), tokens)
    ref = dense.apply(params, tokens)

    sp = TransformerLM(
        vocab=vocab, d_model=16, n_heads=4, d_ff=32, n_layers=2,
        max_len=S, dtype=jnp.float32,
        attention_fn=make_ring_attention_fn("intra"),
    )
    S_local = S // n_sp

    def body(params, tokens):
        offset = lax.axis_index("intra") * S_local
        return sp.apply(params, tokens, position_offset=offset)

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(), P(None, "intra")),
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

def test_zigzag_indices_roundtrip():
    from chainermn_tpu.parallel.ring_attention import (
        inverse_zigzag_indices,
        zigzag_indices,
    )

    S, n = 32, 4
    idx = zigzag_indices(S, n)
    inv = inverse_zigzag_indices(S, n)
    x = np.arange(S)
    np.testing.assert_array_equal(x[idx][inv], x)
    # Shard 0 holds chunks 0 and 2n-1 (early + late).
    c = S // (2 * n)
    shard0 = idx[: 2 * c]
    assert list(shard0[:c]) == list(range(0, c))
    assert list(shard0[c:]) == list(range(S - c, S))


@pytest.mark.slow
def test_zigzag_ring_attention_matches_full(seq_mesh):
    from chainermn_tpu.parallel.ring_attention import (
        inverse_zigzag_indices,
        zigzag_indices,
        zigzag_ring_attention,
    )

    n = 4
    q, k, v = make_qkv(S=32)
    S = q.shape[1]
    idx = zigzag_indices(S, n)
    inv = inverse_zigzag_indices(S, n)
    qz, kz, vz = q[:, idx], k[:, idx], v[:, idx]

    def body(q, k, v):
        return zigzag_ring_attention(q, k, v, "intra")

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(qz, kz, vz)[:, inv]  # back to natural order
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_zigzag_ring_attention_backward(seq_mesh):
    from chainermn_tpu.parallel.ring_attention import (
        zigzag_indices,
        zigzag_ring_attention,
    )

    n = 4
    q, k, v = make_qkv(S=32)
    S = q.shape[1]
    idx = zigzag_indices(S, n)

    def zig_loss(q, k, v):
        def body(q, k, v):
            return zigzag_ring_attention(q, k, v, "intra")

        f = shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
        return jnp.sum(f(q[:, idx], k[:, idx], v[:, idx]) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    gz = jax.jit(jax.grad(zig_loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gz, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("use_flash", [False, True])
@pytest.mark.slow
def test_zigzag_flash_inner_matches_full(seq_mesh, use_flash):
    """The flash-kernel inner loop ("ring outside, flash inside") must
    agree with the dense inner loop and the full-attention oracle, forward
    and backward."""
    from chainermn_tpu.parallel.ring_attention import (
        inverse_zigzag_indices,
        zigzag_indices,
        zigzag_ring_attention,
    )

    n = 4
    q, k, v = make_qkv(S=64, D=16)
    S = q.shape[1]
    idx = zigzag_indices(S, n)
    inv = inverse_zigzag_indices(S, n)

    def zig_loss(q, k, v):
        def body(q, k, v):
            return zigzag_ring_attention(q, k, v, "intra", use_flash=use_flash)

        f = shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
        return f(q[:, idx], k[:, idx], v[:, idx])

    out = jax.jit(zig_loss)(q, k, v)[:, inv]
    ref = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    g = jax.jit(
        jax.grad(lambda q, k, v: jnp.sum(zig_loss(q, k, v) ** 2), argnums=(0, 1, 2))
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(full_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# Packed sequences: segment masks threaded through the SP family
# ---------------------------------------------------------------------------


def segmented_full_attention(q, k, v, seg, causal=True):
    """Dense oracle: segment equality (+ causal) mask; fully-masked rows
    produce zeros."""
    B, S, H, D = q.shape
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D**0.5)
    mask = seg[:, :, None] == seg[:, None, :]
    if causal:
        mask = mask & jnp.tril(jnp.ones((S, S), bool))[None]
    logits = jnp.where(mask[:, None], logits, -jnp.inf)
    w = jax.nn.softmax(logits)
    w = jnp.where(mask.any(-1)[:, None, :, None], w, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _packed_seg(B=2, S=16):
    """Two documents per row with the boundary INSIDE shard 1 (S=16 over
    4 shards of 4: boundary at 6), so masks must cross shard boundaries."""
    seg = np.zeros((B, S), np.int32)
    seg[:, 6:] = 1
    return jnp.asarray(seg)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_segments_match_oracle(seq_mesh, causal):
    q, k, v = make_qkv()
    seg = _packed_seg()

    def body(q, k, v, seg):
        return ring_attention(
            q, k, v, "intra", causal=causal, q_segment_ids=seg,
        )

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 4,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(q, k, v, seg)
    ref = segmented_full_attention(q, k, v, seg, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_segments_gradients(seq_mesh):
    q, k, v = make_qkv()
    seg = _packed_seg()

    def sp_loss(q, k, v):
        def body(q, k, v, seg):
            return ring_attention(
                q, k, v, "intra", causal=True, q_segment_ids=seg,
            )

        out = shard_map(
            body, mesh=seq_mesh, in_specs=(P(None, "intra"),) * 4,
            out_specs=P(None, "intra"), check_vma=False,
        )(q, k, v, seg)
        return jnp.sum(jnp.sin(out))

    def ref_loss(q, k, v):
        return jnp.sum(jnp.sin(segmented_full_attention(q, k, v, seg)))

    g = jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_ulysses_segments_match_oracle(seq_mesh):
    q, k, v = make_qkv()
    seg = _packed_seg()

    def body(q, k, v, seg):
        return ulysses_attention(
            q, k, v, "intra", causal=True, q_segment_ids=seg,
        )

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 4,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(q, k, v, seg)
    ref = segmented_full_attention(q, k, v, seg, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_zigzag_segments_match_oracle(seq_mesh):
    from chainermn_tpu.parallel.ring_attention import (
        zigzag_indices, inverse_zigzag_indices, zigzag_ring_attention,
    )

    B, S = 2, 16
    q, k, v = make_qkv(B=B, S=S)
    seg = _packed_seg(B, S)
    perm = zigzag_indices(S, 4)
    inv = inverse_zigzag_indices(S, 4)
    qz, kz, vz = (t[:, perm] for t in (q, k, v))
    segz = seg[:, perm]

    def body(q, k, v, seg):
        return zigzag_ring_attention(
            q, k, v, "intra", segment_ids=seg, use_flash=False,
        )

    f = jax.jit(
        shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 4,
            out_specs=P(None, "intra"),
            check_vma=False,
        )
    )
    out = f(qz, kz, vz, segz)[:, inv]
    ref = segmented_full_attention(q, k, v, seg, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_zigzag_segments_flash_inner_matches_dense(seq_mesh):
    """The segmented FLASH inner (flash_attention_with_lse_seg inside the
    zigzag ring) must match the dense inner exactly — fwd and bwd."""
    from chainermn_tpu.parallel.ring_attention import (
        zigzag_indices, zigzag_ring_attention,
    )

    B, S = 2, 1024  # chunk C=128: satisfies the interpret block plan
    q, k, v = make_qkv(B=B, S=S, H=2, D=8)
    seg = np.zeros((B, S), np.int32)
    seg[:, 300:] = 1  # boundary inside shard 1
    perm = zigzag_indices(S, 4)
    qz, kz, vz = (t[:, perm] for t in (q, k, v))
    segz = jnp.asarray(seg[:, perm])

    def run(use_flash):
        def body(q, k, v, seg):
            return zigzag_ring_attention(
                q, k, v, "intra", segment_ids=seg, use_flash=use_flash,
            )

        f = shard_map(
            body, mesh=seq_mesh, in_specs=(P(None, "intra"),) * 4,
            out_specs=P(None, "intra"), check_vma=False,
        )

        def loss(q, k, v):
            return jnp.sum(jnp.sin(f(q, k, v, segz)))

        out = jax.jit(f)(qz, kz, vz, segz)
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(qz, kz, vz)
        return out, g

    out_f, g_f = run(True)
    out_d, g_d = run(False)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=2e-5, atol=2e-5
    )
    for a, b in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


# ---------------------------------------------------------------------------
# GQA/MQA through the sequence-parallel layers (VERDICT r4 item 5)
# ---------------------------------------------------------------------------


def make_gqa_qkv(B=2, S=16, H=4, Hk=2, D=8, seed=7):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hk, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hk, D), jnp.float32)
    return q, k, v


def full_attention_gqa(q, k, v, causal=True):
    G = q.shape[2] // k.shape[2]
    return full_attention(
        q, jnp.repeat(k, G, axis=2), jnp.repeat(v, G, axis=2), causal=causal
    )


@pytest.mark.parametrize("Hk", [1, 2])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gqa_matches_full(seq_mesh, causal, Hk):
    """Only the reduced kv blocks rotate; outputs must match the
    broadcast oracle."""
    q, k, v = make_gqa_qkv(Hk=Hk)

    def body(q, k, v):
        return ring_attention(q, k, v, "intra", causal=causal)

    out = jax.jit(shard_map(
        body, mesh=seq_mesh,
        in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
        check_vma=False,
    ))(q, k, v)
    ref = full_attention_gqa(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_gqa_gradients(seq_mesh):
    q, k, v = make_gqa_qkv(Hk=2)

    def ring_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "intra", causal=True),
            mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
            check_vma=False,
        )
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(full_attention_gqa(q, k, v, causal=True) ** 2)

    g1 = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert a.shape == b.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


@pytest.mark.parametrize("Hk", [4])
def test_ulysses_gqa_matches_full(seq_mesh, Hk):
    """Ulysses deals kv heads across chips too: Hk must divide the axis
    size (here n=4, so Hk=4 with H=8)."""
    q, k, v = make_gqa_qkv(H=8, Hk=Hk)

    def body(q, k, v):
        return ulysses_attention(q, k, v, "intra", causal=True)

    out = jax.jit(shard_map(
        body, mesh=seq_mesh,
        in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
        check_vma=False,
    ))(q, k, v)
    ref = full_attention_gqa(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ulysses_gqa_rejects_indivisible_kv_heads(seq_mesh):
    q, k, v = make_gqa_qkv(H=8, Hk=2)  # Hk=2 < n=4

    def body(q, k, v):
        return ulysses_attention(q, k, v, "intra", causal=True)

    with pytest.raises(ValueError, match="kv head"):
        jax.jit(shard_map(
            body, mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
            check_vma=False,
        ))(q, k, v)


def test_zigzag_gqa_matches_full(seq_mesh):
    from chainermn_tpu.parallel.ring_attention import (
        inverse_zigzag_indices,
        zigzag_indices,
        zigzag_ring_attention,
    )

    n = 4
    q, k, v = make_gqa_qkv(S=32, Hk=2)
    S = q.shape[1]
    idx = zigzag_indices(S, n)
    inv = inverse_zigzag_indices(S, n)

    def body(q, k, v):
        return zigzag_ring_attention(q, k, v, "intra")

    out = jax.jit(shard_map(
        body, mesh=seq_mesh,
        in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
        check_vma=False,
    ))(q[:, idx], k[:, idx], v[:, idx])[:, inv]
    ref = full_attention_gqa(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_transformer_lm_gqa_matches_repeat_oracle():
    """TransformerLM(n_kv_heads=...) trains the reduced K/V projections;
    logits must match manually broadcasting those projections through the
    MHA dense path."""
    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.ops.flash_attention import make_flash_attention_fn

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 32, size=(2, 16)), jnp.int32)
    base = dict(vocab=32, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                max_len=16, dtype=jnp.float32)
    gqa_dense = TransformerLM(**base, n_kv_heads=2)
    gqa_flash = TransformerLM(
        **base, n_kv_heads=2,
        attention_fn=make_flash_attention_fn(causal=True),
    )
    params = gqa_dense.init(jax.random.PRNGKey(0), tokens)["params"]
    # K/V kernels really are reduced-width.
    assert params["layer_0"]["MultiHeadAttention_0"]["key"]["kernel"].shape \
        == (32, 2, 8)
    out_dense = gqa_dense.apply({"params": params}, tokens)
    out_flash = gqa_flash.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_flash), rtol=2e-3, atol=2e-3
    )


def test_ulysses_window_matches_banded_oracle(seq_mesh):
    """Sliding window through ulysses: the head all-to-all leaves the
    full sequence local, so the kernel's global band applies exactly."""
    q, k, v = make_qkv(S=32)
    window = 10

    def body(q, k, v):
        return ulysses_attention(q, k, v, "intra", causal=True,
                                 window=window)

    out = jax.jit(shard_map(
        body, mesh=seq_mesh,
        in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
        check_vma=False,
    ))(q, k, v)

    S = q.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (q.shape[-1] ** 0.5)
    qp = jnp.arange(S)[:, None]
    kp = jnp.arange(S)[None, :]
    band = (qp >= kp) & (qp - kp < window)
    logits = jnp.where(band[None, None], logits, -jnp.inf)
    w = jax.nn.softmax(logits)
    ref = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_window_matches_banded_oracle(seq_mesh):
    """Sliding window across ring shard boundaries: the global-position
    block masks carry the band exactly (window 10 spans the 8-token
    shards of the 4-way ring)."""
    from chainermn_tpu.parallel.ring_attention import ring_attention as ra

    q, k, v = make_qkv(S=32)
    window = 10

    out = jax.jit(shard_map(
        lambda q, k, v: ra(q, k, v, "intra", causal=True, window=window),
        mesh=seq_mesh,
        in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
        check_vma=False,
    ))(q, k, v)

    S = q.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (q.shape[-1] ** 0.5)
    qp = jnp.arange(S)[:, None]
    kp = jnp.arange(S)[None, :]
    band = (qp >= kp) & (qp - kp < window)
    logits = jnp.where(band[None, None], logits, -jnp.inf)
    w = jax.nn.softmax(logits)
    ref = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_ring_attention_window_gradients(seq_mesh):
    from chainermn_tpu.parallel.ring_attention import ring_attention as ra

    q, k, v = make_qkv(S=32)
    window = 10

    def ring_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ra(q, k, v, "intra", causal=True,
                               window=window),
            mesh=seq_mesh,
            in_specs=(P(None, "intra"),) * 3, out_specs=P(None, "intra"),
            check_vma=False,
        )
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        S = q.shape[1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (q.shape[-1] ** 0.5)
        qp = jnp.arange(S)[:, None]
        kp = jnp.arange(S)[None, :]
        band = (qp >= kp) & (qp - kp < window)
        logits = jnp.where(band[None, None], logits, -jnp.inf)
        w = jax.nn.softmax(logits)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", w, v) ** 2)

    g1 = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )
