"""Worker for the kill -9 fault-tolerance test (VERDICT r3 item #1).

Run as: python _mp_resume_worker.py <pid> <nproc> <port> <ckpt_dir> <crash_after>

Runs the REAL examples/imagenet training CLI (tiny config) under a
2-process jax.distributed world.  With ``crash_after > 0`` the process
hard-kills itself (SIGKILL — no atexit, no flushing, exactly a crash)
once a consistent checkpoint generation >= crash_after exists on disk;
with ``crash_after == 0`` it runs to completion and the example prints
``final gstep N params_digest XXXXXXXX``.  The test asserts a relaunch
resumes mid-run and reproduces the uninterrupted run's digest
bit-for-bit (reference behavior: REF:chainermn/extensions/checkpoint.py
maybe_load, SURVEY §5.3-§5.4).
"""

import os
import sys


def main():
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    port, ckpt_dir = sys.argv[3], sys.argv[4]
    crash_after = int(sys.argv[5])

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    flags.append("--xla_force_host_platform_device_count=2")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )

    if crash_after > 0:
        import re
        import signal
        import time

        from chainermn_tpu.extensions import checkpoint as ckpt_mod

        orig_save = ckpt_mod.MultiNodeCheckpointer.save

        def save_then_maybe_die(self, state, iteration, block=True):
            orig_save(self, state, iteration, block=block)
            if iteration < crash_after:
                return
            self.wait()  # our own generation committed
            pat = re.compile(r"done_iter_(\d+)\.rank(\d+)$")
            deadline = time.time() + 60
            while time.time() < deadline:
                gens = {}
                for fn in os.listdir(self.dir):
                    m = pat.match(fn)
                    if m:
                        gens.setdefault(int(m.group(1)), set()).add(
                            int(m.group(2))
                        )
                if any(
                    it >= crash_after and len(ranks) >= self.comm.size
                    for it, ranks in gens.items()
                ):
                    os.kill(os.getpid(), signal.SIGKILL)  # CRASH.
                time.sleep(0.05)
            raise RuntimeError("consistent generation never appeared")

        ckpt_mod.MultiNodeCheckpointer.save = save_then_maybe_die

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples", "imagenet",
        ),
    )
    import train_imagenet

    train_imagenet.main([
        "--communicator", "naive", "--arch", "nin", "--image-size", "64",
        "--num-classes", "10", "--batchsize", "32", "--train-size", "128",
        "--val-size", "32", "--epochs", "2", "--warmup-steps", "4",
        "--prefetch", "0",
        "--checkpoint-dir", ckpt_dir, "--checkpoint-every", "1",
    ])
    print(f"RESUME_WORKER_DONE {pid}", flush=True)


if __name__ == "__main__":
    main()
