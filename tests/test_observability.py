"""Telemetry subsystem: Reporter semantics and aggregation, StepRecorder
file contract (atomic append / rotation / crash recovery), hlo_audit
census parity with the communicator bandwidth claims, span fan-out, and
the ``tools.obs`` CLI (JSON summary + Prometheus textfile).

Cross-PROCESS Reporter aggregation runs in tests/_mp_worker.py (the real
multi-process harness); here the communicators are single-process, where
``aggregate`` takes the trivial object-plane path.
"""

import json
import math
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.observability import (
    AnomalyDetector,
    MetricsExporter,
    Reporter,
    StepRecorder,
    audit_allreduce,
    audit_fn,
    get_reporter,
    merge_summaries,
    read_records,
    recover,
    report,
    scope,
    span,
    telemetry_active,
)
from chainermn_tpu.observability.reporter import _bucket
from chainermn_tpu.tools.obs import metric_diff, summarize, to_prometheus


# ---------------------------------------------------------------------------
# Reporter
# ---------------------------------------------------------------------------

def test_reporter_scalar_semantics():
    r = Reporter()
    for v in (3.0, 1.0, 2.0):
        r.observe("loss", v)
    s = r.summary()["scalars"]["loss"]
    assert s["count"] == 3
    assert s["sum"] == 6.0
    assert s["min"] == 1.0 and s["max"] == 3.0
    assert s["last"] == 2.0
    assert s["mean"] == 2.0


def test_reporter_counters_and_histograms():
    r = Reporter()
    r.count("steps")
    r.count("steps", 4)
    r.histogram_observe("lat", 0.75)   # ceil(log2(0.75)) = 0
    r.histogram_observe("lat", 3.0)    # ceil(log2(3)) = 2
    r.histogram_observe("lat", 0.0)    # non-positive -> lowest bucket
    s = r.summary()
    assert s["counters"]["steps"] == 5
    assert s["histograms"]["lat"] == {"0": 1, "2": 1, "-30": 1}


def test_bucket_clamps():
    assert _bucket(-1.0) == -30
    assert _bucket(2.0**100) == 63
    assert _bucket(1.0) == 0
    assert _bucket(2.0) == 1


def test_merge_summaries_weighted_mean():
    a, b = Reporter(), Reporter()
    a.observe("loss", 1.0)
    a.observe("loss", 3.0)
    b.observe("loss", 5.0)
    b.count("steps", 2)
    a.count("steps", 1)
    m = merge_summaries([a.summary(), b.summary()])
    assert m["scalars"]["loss"]["count"] == 3
    assert m["scalars"]["loss"]["mean"] == pytest.approx(3.0)
    assert m["scalars"]["loss"]["min"] == 1.0
    assert m["scalars"]["loss"]["max"] == 5.0
    assert m["counters"]["steps"] == 3


def test_aggregate_single_process_trivial_path():
    import chainermn_tpu

    comm = chainermn_tpu.create_communicator("naive")
    r = Reporter()
    r.observe("x", 2.0)
    agg = r.aggregate(comm)
    assert agg["scalars"]["x"]["mean"] == 2.0
    # reset=True clears after the merge
    r.aggregate(comm, reset=True)
    assert r.summary()["scalars"] == {}


def test_reporter_scope_stack():
    assert get_reporter() is None
    assert not telemetry_active()
    r = Reporter()
    with scope(r):
        assert get_reporter() is r
        assert telemetry_active()
        report({"a": 1.0})
    assert get_reporter() is None
    report({"a": 1.0})  # no-op, must not raise
    assert r.summary()["scalars"]["a"]["count"] == 1


# ---------------------------------------------------------------------------
# StepRecorder / JSONL file contract
# ---------------------------------------------------------------------------

def _mk_recorder(tmp_path, **kw):
    kw.setdefault("capture_compile_events", False)
    return StepRecorder(str(tmp_path / "steps.jsonl"), **kw)


def test_recorder_rows_and_step_derivations(tmp_path):
    clock = iter([10.0, 10.5, 11.5])
    rec = _mk_recorder(tmp_path, mem_every=0, clock=lambda: next(clock))
    with rec:
        rec.step(step=0, items=64, loss=np.float32(1.5))
        r1 = rec.step(step=1, items=64, loss=jnp.float32(0.5))
        r2 = rec.step(step=2, items=128)
    rows = read_records(rec.path)
    assert [r["step"] for r in rows] == [0, 1, 2]
    assert "dt" not in rows[0]  # no previous step to diff against
    assert r1["dt"] == pytest.approx(0.5)
    assert r1["per_sec"] == pytest.approx(128.0)
    assert r2["dt"] == pytest.approx(1.0)
    # numpy/jax scalars serialized as plain floats
    assert isinstance(rows[0]["loss"], float) and rows[0]["loss"] == 1.5
    assert rows[1]["loss"] == 0.5


def test_recorder_rotation_bounds_files(tmp_path):
    rec = _mk_recorder(tmp_path, rotate_bytes=400, max_files=3)
    with rec:
        for i in range(60):
            rec.record("e", i=i, pad="x" * 40)
    segs = sorted(
        p for p in os.listdir(tmp_path) if p.startswith("steps.jsonl")
    )
    assert "steps.jsonl" in segs
    assert f"steps.jsonl.{rec.max_files - 1}" in segs
    assert len(segs) <= rec.max_files
    rows = read_records(rec.path)
    # Retained rows are the TAIL of the stream, in order.
    idx = [r["i"] for r in rows]
    assert idx == sorted(idx)
    assert idx[-1] == 59
    # Oldest→newest ordering across segments: the rotated segment's rows
    # precede the live file's.
    live = read_records(rec.path, include_rotated=False)
    assert live[-1]["i"] == 59
    assert len(live) < len(rows)


def test_recorder_crash_recovery(tmp_path):
    rec = _mk_recorder(tmp_path)
    with rec:
        rec.record("a", i=0)
        rec.record("b", i=1)
    # Simulate a SIGKILL mid-write: a trailing unterminated partial line.
    with open(rec.path, "a") as f:
        f.write('{"event": "c", "i": 2')
    rows = read_records(rec.path)  # reader skips the torn tail
    assert [r["event"] for r in rows] == ["a", "b"]
    with pytest.raises(ValueError):
        read_records(rec.path, strict=True)
    assert recover(rec.path) == 2  # truncates in place, counts valid rows
    assert read_records(rec.path, strict=True) == rows
    # A resumed recorder appends to the recovered file cleanly.
    rec2 = _mk_recorder(tmp_path)
    with rec2:
        rec2.record("d", i=3)
    assert [r["event"] for r in read_records(rec.path)] == ["a", "b", "d"]


def test_recorder_missing_file_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        read_records(str(tmp_path / "nope.jsonl"))


def test_span_feeds_reporter_and_recorder(tmp_path):
    r = Reporter()
    rec = _mk_recorder(tmp_path)
    with scope(r), rec:
        with span("work"):
            pass
        row = rec.step(step=0)
    assert r.summary()["scalars"]["span/work"]["count"] == 1
    assert "work" in row["spans"]
    assert row["spans"]["work"] >= 0.0


@pytest.mark.slow
def test_recorder_rotation_soak(tmp_path):
    """Soak: tens of thousands of rows through a small rotation window —
    segment count stays bounded and the retained tail stays parseable."""
    rec = _mk_recorder(tmp_path, rotate_bytes=4096, max_files=4)
    with rec:
        for i in range(30_000):
            rec.record("e", i=i)
    segs = [p for p in os.listdir(tmp_path) if p.startswith("steps.jsonl")]
    assert len(segs) <= 4
    rows = read_records(rec.path)
    assert rows[-1]["i"] == 29_999
    idx = [r["i"] for r in rows]
    assert idx == sorted(idx)


# ---------------------------------------------------------------------------
# hlo_audit
# ---------------------------------------------------------------------------

def _comm(name):
    import chainermn_tpu

    return chainermn_tpu.create_communicator(name)


def test_audit_allreduce_flat_census(devices8):
    audit = audit_allreduce(_comm("flat"), 1 << 20)
    c = audit.census()
    assert set(c) == {"psum", "reduce_scatter", "all_gather", "ppermute"}
    assert c["psum"] == 1 and c["reduce_scatter"] == 0


def test_audit_two_dimensional_inter_savings(devices8):
    """The bench's headline static claim, now via the library: the 2D
    backend's inter-axis operand bytes are flat's divided by intra."""
    nbytes = 1 << 20
    flat = audit_allreduce(_comm("flat"), nbytes)
    td = audit_allreduce(_comm("two_dimensional"), nbytes)
    intra = _comm("flat").intra_size
    assert flat.bytes_per_axis["inter"] == nbytes
    assert td.bytes_per_axis["inter"] * intra == nbytes
    assert td.counts.get("reduce_scatter", 0) >= 1
    assert td.counts.get("all_gather", 0) >= 1


def test_audit_fn_on_jitted_step(devices8):
    """audit_fn traces through jit and charges bytes to mesh axes."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    comm = _comm("flat")

    def body(x):
        return lax.psum(x, comm.axes)

    fn = jax.jit(comm.shard_map(
        body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
    ))
    x = jnp.ones((8, 256), jnp.float32)
    audit = audit_fn(fn, x)
    assert audit.counts.get("psum") == 1
    # per-device operand: (1, 256) float32 = 1 KiB charged to both axes
    assert audit.bytes_per_axis["inter"] == 1024
    assert audit.bytes_per_axis["intra"] == 1024
    summ = audit.summary()
    assert summ["counts"]["psum"] == 1


def test_audit_fn_no_collectives():
    import jax

    audit = audit_fn(jax.jit(lambda x: x * 2), jnp.ones((4,)))
    assert audit.counts == {}
    assert audit.census()["psum"] == 0


def test_bench_bytes_per_leg_parity(devices8):
    """The allreduce_bench wrappers and the library agree exactly — one
    source of truth for ``allreduce_static_bytes_per_leg``."""
    bench_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
    )
    sys.path.insert(0, bench_dir)
    try:
        from allreduce_bench import bytes_per_leg, collective_profile
    finally:
        sys.path.remove(bench_dir)
    comm = _comm("two_dimensional")
    nbytes = 1 << 20
    audit = audit_allreduce(comm, nbytes, np.float32)
    assert bytes_per_leg(comm, nbytes, np.float32) == audit.bytes_per_axis
    assert collective_profile(comm, nbytes, np.float32) == audit.census()


# ---------------------------------------------------------------------------
# tools.obs CLI
# ---------------------------------------------------------------------------

def _write_rows(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


_CLI_ROWS = [
    {"event": "start", "rank": 0, "t": 0.0},
    {"event": "hlo_audit", "rank": 0, "t": 0.0,
     "counts": {"psum": 2}, "bytes_per_axis": {"inter": 1024, "intra": 2048}},
    {"event": "step", "rank": 0, "t": 1.0, "step": 0, "items": 32,
     "loss": 4.0, "spans": {"fwd": 0.25}},
    {"event": "step", "rank": 0, "t": 2.0, "step": 1, "items": 32,
     "loss": 2.0, "dt": 0.5, "per_sec": 64.0, "spans": {"fwd": 0.25}},
    {"event": "step", "rank": 0, "t": 3.0, "step": 2, "items": 32,
     "loss": 1.0, "dt": 0.5, "per_sec": 64.0},
    {"event": "compile", "rank": 0, "t": 0.5, "name": "x", "secs": 2.0},
]


def test_summarize_core_numbers(tmp_path):
    p = tmp_path / "log.jsonl"
    _write_rows(p, _CLI_ROWS)
    s = summarize(read_records(str(p)))
    assert s["steps"]["count"] == 3
    assert s["steps"]["wall_s"] == pytest.approx(1.0)
    assert s["steps"]["per_sec"] == pytest.approx(2.0)
    assert s["loss"] == {
        "first": 4.0, "last": 1.0, "min": 1.0,
        "curve": [[0, 4.0], [1, 2.0], [2, 1.0]],
    }
    assert s["spans"]["fwd"] == {"total_s": 0.5, "count": 2}
    assert s["compile"] == {"count": 1, "total_s": 2.0}
    assert s["collectives"]["counts"] == {"psum": 2}


def test_summarize_rank_aggregation_matches_single_process(tmp_path):
    """Two rank logs carrying the same per-step global loss summarize to
    the same loss values as one single-process log — the acceptance
    contract for multi-host step logs."""
    single = [
        {"event": "step", "rank": 0, "step": i, "loss": float(10 - i),
         "dt": 0.5, "items": 8}
        for i in range(4)
    ]
    r0 = tmp_path / "r0.jsonl"
    r1 = tmp_path / "r1.jsonl"
    mono = tmp_path / "mono.jsonl"
    _write_rows(mono, single)
    _write_rows(r0, single)
    _write_rows(r1, [dict(r, rank=1) for r in single])
    s_mono = summarize(read_records(str(mono)))
    s_multi = summarize(
        read_records(str(r0)) + read_records(str(r1))
    )
    assert s_multi["loss"] == s_mono["loss"]
    assert s_multi["steps"]["count"] == s_mono["steps"]["count"]
    assert s_multi["steps"]["wall_s"] == pytest.approx(
        s_mono["steps"]["wall_s"]
    )
    assert s_multi["steps"]["items_per_sec"] == pytest.approx(
        s_mono["steps"]["items_per_sec"]
    )
    assert s_multi["ranks"] == [0, 1]


def test_loss_curve_downsampling(tmp_path):
    rows = [
        {"event": "step", "rank": 0, "step": i, "loss": float(i), "dt": 1.0}
        for i in range(100)
    ]
    p = tmp_path / "log.jsonl"
    _write_rows(p, rows)
    s = summarize(read_records(str(p)), curve_points=16)
    curve = s["loss"]["curve"]
    assert len(curve) <= 17  # 16 strided points + appended last
    assert curve[0] == [0, 0.0]
    assert curve[-1] == [99, 99.0]


PROM_GOLDEN = """\
# HELP t_steps_total Training steps recorded
# TYPE t_steps_total counter
t_steps_total 3
# HELP t_step_seconds_sum Sum of host-side step durations
# TYPE t_step_seconds_sum counter
t_step_seconds_sum 1
# HELP t_step_seconds_mean Mean step duration
# TYPE t_step_seconds_mean gauge
t_step_seconds_mean 0.5
# HELP t_steps_per_second Steps per second
# TYPE t_steps_per_second gauge
t_steps_per_second 2
# HELP t_items_per_second Items (tokens or images) per second
# TYPE t_items_per_second gauge
t_items_per_second 96
# HELP t_loss_last Last recorded loss
# TYPE t_loss_last gauge
t_loss_last 1
# HELP t_loss_min Minimum recorded loss
# TYPE t_loss_min gauge
t_loss_min 1
# HELP t_compile_events_total jax.monitoring compile events
# TYPE t_compile_events_total counter
t_compile_events_total 1
# HELP t_compile_seconds_total Total compile seconds
# TYPE t_compile_seconds_total counter
t_compile_seconds_total 2
# HELP t_span_seconds_total Host-side span durations
# TYPE t_span_seconds_total counter
t_span_seconds_total{span="fwd"} 0.5
# HELP t_collective_ops_total Collective primitives in the audited step program
# TYPE t_collective_ops_total counter
t_collective_ops_total{primitive="psum"} 2
# HELP t_collective_operand_bytes Per-device collective operand bytes per mesh axis
# TYPE t_collective_operand_bytes gauge
t_collective_operand_bytes{axis="inter"} 1024
t_collective_operand_bytes{axis="intra"} 2048
"""


def test_prometheus_golden(tmp_path):
    p = tmp_path / "log.jsonl"
    _write_rows(p, _CLI_ROWS)
    text = to_prometheus(summarize(read_records(str(p))), prefix="t")
    assert text == PROM_GOLDEN


def test_obs_cli_subprocess(tmp_path):
    """The installed entry point end-to-end: summarize prints one JSON
    object; prom writes the textfile."""
    p = tmp_path / "log.jsonl"
    _write_rows(p, _CLI_ROWS)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.obs", "summarize",
         str(p)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    s = json.loads(out.stdout)
    assert s["steps"]["count"] == 3
    prom = tmp_path / "log.prom"
    out = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.obs", "prom", str(p),
         "-o", str(prom), "--prefix", "t"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert prom.read_text() == PROM_GOLDEN


# ---------------------------------------------------------------------------
# profiling degradation (satellite: trace/annotate without jax.profiler)
# ---------------------------------------------------------------------------

def test_trace_and_annotate_degrade_without_profiler(monkeypatch, tmp_path):
    import jax

    from chainermn_tpu.utils import profiling

    monkeypatch.delattr(jax, "profiler", raising=False)
    ran = []
    with profiling.trace(str(tmp_path / "trace")) as logdir:
        ran.append(logdir)
    assert ran  # block ran, logdir still yielded
    with profiling.annotate("region"):
        ran.append("annotated")
    assert "annotated" in ran


def test_compilation_cache_env_override(monkeypatch, tmp_path):
    import jax

    from chainermn_tpu.utils.profiling import setup_compilation_cache

    target = str(tmp_path / "cache")
    monkeypatch.setenv("CHAINERMN_TPU_JAX_CACHE", target)
    setup_compilation_cache()
    assert jax.config.jax_compilation_cache_dir == target


def test_instrumented_step_counts_calls(devices8):
    import chainermn_tpu
    import optax

    comm = _comm("flat")
    opt = chainermn_tpu.create_multi_node_optimizer(optax.sgd(0.1), comm)
    params = {"w": jnp.ones((8, 2))}
    state = opt.init(params)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((x @ p["w"] - y) ** 2)

    step = opt.make_train_step(loss_fn)
    batch = (jnp.ones((16, 8)), jnp.zeros((16, 2)))
    # telemetry off: plain call, no reporter interaction
    params, state, _ = step(params, state, batch)
    r = Reporter()
    with scope(r):
        params, state, _ = step(params, state, batch)
        params, state, _ = step(params, state, batch)
    s = r.summary()
    assert s["counters"]["train_step_calls"] == 2
    assert s["scalars"]["span/train_step"]["count"] == 2


def test_evaluator_reports_through_reporter(devices8, tmp_path):
    import chainermn_tpu
    from chainermn_tpu.extensions import Evaluator

    comm = _comm("flat")

    def metric_fn(params, batch):
        (x,) = batch
        return {"val/m": jnp.mean(x * params)}

    ev = Evaluator(metric_fn, comm)
    r = Reporter()
    rec = _mk_recorder(tmp_path)
    with scope(r), rec:
        out = ev.evaluate(jnp.float32(2.0), [(jnp.ones((8, 4)),)])
    assert out["val/m"] == pytest.approx(2.0)
    s = r.summary()
    assert s["scalars"]["eval/val/m"]["last"] == pytest.approx(2.0)
    assert s["scalars"]["span/evaluate"]["count"] == 1
    rows = [x for x in read_records(rec.path) if x["event"] == "eval"]
    assert rows and rows[0]["metrics"]["val/m"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# Fleet-live plane: scrape endpoint, native histograms, stale-series
# hygiene, anomaly detection, and the ``obs diff`` regression gate
# ---------------------------------------------------------------------------


def _scrape(url):
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        return resp.read().decode()


def test_native_histogram_exposition_cumulative():
    """Pow2 histograms render as a real Prometheus histogram: cumulative
    ``le`` buckets at exact 2^b upper bounds, +Inf, _sum, _count."""
    r = Reporter()
    for v in (0.75, 3.0, 3.5, 0.0):  # buckets 0, 2, 2, -30
        r.histogram_observe("trace/decode", v)
    text = to_prometheus(r.summary())
    assert "# TYPE chainermn_tpu_histogram histogram" in text
    rows = [ln for ln in text.splitlines()
            if ln.startswith("chainermn_tpu_histogram")]
    import re

    cums = [int(m.group(1)) for m in (
        re.search(r"} (\d+)$", ln) for ln in rows if "_bucket" in ln
    )]
    assert cums == [1, 2, 4, 4]  # le=2^-30, le=1, le=4, le=+Inf
    bounds = re.findall(r'le="([^"]+)"', "\n".join(rows))
    assert bounds[1:] == ["1", "4", "+Inf"]
    assert float(bounds[0]) == pytest.approx(2.0 ** -30)
    (sum_row,) = [ln for ln in rows if "_sum" in ln]
    assert float(sum_row.rsplit(" ", 1)[1]) == pytest.approx(9.0, rel=1e-6)
    (count_row,) = [ln for ln in rows if "_count" in ln]
    assert count_row.endswith(" 4")


def test_native_histogram_replica_label_split():
    r = Reporter()
    r.histogram_observe("trace/decode/replica/3", 2.0)
    text = to_prometheus(r.summary())
    assert 'name="trace/decode",replica="3"' in text
    assert "trace/decode/replica/3" not in text


def test_metrics_exporter_scrape_counters_move():
    """Two scrapes of a live endpoint observe the counter move — the
    pull-model smoke test."""
    r = Reporter()
    r.count("serving/steps", 3)
    exp = MetricsExporter(r, port=0)
    port = exp.start()
    try:
        assert exp.url == f"http://127.0.0.1:{port}/metrics"
        assert exp.start() == port  # idempotent
        t1 = _scrape(exp.url)
        assert 'chainermn_tpu_counter_total{name="serving/steps"} 3' in t1
        r.count("serving/steps", 2)
        r.gauge("serving/queue_depth", 4)
        t2 = _scrape(exp.url)
        assert 'chainermn_tpu_counter_total{name="serving/steps"} 5' in t2
        assert 'chainermn_tpu_gauge{name="serving/queue_depth"} 4' in t2
    finally:
        exp.stop()
    exp.stop()  # idempotent after shutdown


def test_metrics_exporter_callable_source_and_404():
    """A zero-arg callable works as the source (the router's fleet-view
    hook); non-metrics paths 404; bad sources are rejected."""
    import urllib.error
    import urllib.request

    calls = []

    def source():
        calls.append(1)
        return {"counters": {"fleet/scrapes": len(calls)}}

    with MetricsExporter(source, port=0) as exp:
        assert 'name="fleet/scrapes"} 1' in _scrape(exp.url)
        assert 'name="fleet/scrapes"} 2' in _scrape(exp.url)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                exp.url.replace("/metrics", "/nope"), timeout=10
            )
        assert ei.value.code == 404
    with pytest.raises(TypeError):
        MetricsExporter(42)


def test_forget_replica_drops_only_that_replicas_series():
    """The stale-series fix: a dead replica's series leave every table,
    without touching a replica whose id merely shares a prefix."""
    r = Reporter()
    r.gauge("serving/running/replica/2", 3)
    r.gauge("serving/running/replica/12", 1)
    r.count("serving/steps", 7)
    r.count("trace/stage/replica/2/decode", 1)  # id as a path segment
    r.histogram_observe("trace/decode/replica/2", 1.0)
    r.observe("lat/replica/2", 0.5)
    assert r.forget_replica(2) == 4
    s = r.summary()
    assert "serving/running/replica/2" not in s["gauges"]
    assert s["gauges"]["serving/running/replica/12"]["value"] == 1
    assert s["counters"] == {"serving/steps": 7}
    assert s["histograms"] == {}
    assert "lat/replica/2" not in s["scalars"]
    assert r.forget_replica(2) == 0


def _fleet_summary(tokens, hist=None):
    return {
        "counters": {"serving/tokens": tokens},
        "histograms": {
            "trace/decode": {str(b): c for b, c in (hist or {}).items()}
        },
    }


def test_anomaly_latency_regression_edge_counted_once():
    """Median of NEW observations rising past regression_factor x the
    baseline median alarms; the counter records the onset once while
    the gauge tracks the level."""
    rep = Reporter()
    det = AnomalyDetector(reporter=rep, window=2, baseline=8,
                          min_samples=2, regression_factor=2.0)
    hist = {0: 0}
    for i in range(6):  # healthy: one new bucket-0 obs (median 1.0)
        hist[0] += 1
        st = det.update(_fleet_summary(0, hist), now=float(i))
        assert not st["latency_regression"]
    assert not det.alarming()
    hist[3] = 0
    for i in range(6, 8):  # regression: new obs in bucket 3 (8x)
        hist[3] += 1
        st = det.update(_fleet_summary(0, hist), now=float(i))
    assert st["latency_regression"] and det.alarming()
    assert st["latency_ratio"] == pytest.approx(8.0)
    s = rep.summary()
    assert s["counters"]["anomaly/latency_regression"] == 1
    assert s["gauges"]["anomaly/latency_regression"]["value"] == 1.0
    # still alarming next tick: level stays, onset is not re-counted
    hist[3] += 1
    det.update(_fleet_summary(0, hist), now=8.0)
    assert rep.summary()["counters"]["anomaly/latency_regression"] == 1
    # recovery clears the gauge
    for i in range(9, 15):
        hist[0] += 1
        det.update(_fleet_summary(0, hist), now=float(i))
    assert not det.alarming()
    assert rep.summary()["gauges"][
        "anomaly/latency_regression"]["value"] == 0.0


def test_anomaly_goodput_drop_and_membership_step_down():
    """Token rate falling below drop_factor x baseline alarms; a merged
    counter stepping DOWN (a replica leaving the fleet view) reads as
    zero rate, never negative."""
    det = AnomalyDetector(window=2, baseline=8, min_samples=2,
                          drop_factor=0.5)
    tokens = 0.0
    st = None
    for i in range(6):  # 100 tokens/s baseline
        tokens += 100.0
        st = det.update(_fleet_summary(tokens), now=float(i))
        assert not st["goodput_drop"]
    for i in range(6, 8):  # collapse to 10 tokens/s
        tokens += 10.0
        st = det.update(_fleet_summary(tokens), now=float(i))
    assert st["goodput_drop"] and det.alarming()
    assert st["goodput_ratio"] == pytest.approx(0.1)
    # fleet-membership step-down: no crash, clamped to zero rate
    st = det.update(_fleet_summary(tokens - 500.0), now=9.0)
    assert st["goodput_ratio"] is not None and st["goodput_ratio"] >= 0.0


def test_anomaly_source_callable_and_no_source_error():
    det = AnomalyDetector()
    with pytest.raises(ValueError):
        det.update()
    fleet = {"n": 0.0}

    def source():
        fleet["n"] += 50.0
        return _fleet_summary(fleet["n"])

    det2 = AnomalyDetector(source=source, window=2, baseline=8,
                           min_samples=2)
    for i in range(4):
        det2.update(now=float(i))
    assert not det2.alarming()


def test_metric_diff_directional_gate():
    a = {"latency_p99_s": 1.0, "tokens_per_sec": 100.0, "widgets": 3.0}
    b = {"latency_p99_s": 1.5, "tokens_per_sec": 100.0, "widgets": 4.0}
    d = metric_diff(a, b, threshold=0.05)
    assert not d["ok"]
    assert [r["key"] for r in d["regressions"]] == ["latency_p99_s"]
    # directionless leaves report as changed but never gate
    assert [r["key"] for r in d["changed"]] == ["widgets"]
    # the same movement in reverse is an improvement, not a regression
    d2 = metric_diff(b, a, threshold=0.05)
    assert d2["ok"]
    assert [r["key"] for r in d2["improvements"]] == ["latency_p99_s"]
    # throughput drops gate too (higher-is-better heuristic)
    d3 = metric_diff({"goodput_tps": 100.0}, {"goodput_tps": 80.0})
    assert not d3["ok"]


def test_obs_diff_cli_exit_codes(tmp_path):
    """The regression gate: nonzero exit + JSON report on a seeded
    regression, zero on self-compare."""
    from chainermn_tpu.tools import obs

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(
        {"serving": {"latency_p99_s": 1.0, "goodput_tps": 50.0}}
    ))
    b.write_text(json.dumps(
        {"serving": {"latency_p99_s": 2.0, "goodput_tps": 50.0}}
    ))
    out = tmp_path / "diff.json"
    rc = obs.main(["diff", str(a), str(b), "--threshold", "0.1",
                   "-o", str(out)])
    assert rc == 1
    rep = json.loads(out.read_text())
    assert not rep["ok"]
    assert rep["regressions"][0]["key"] == "serving.latency_p99_s"
    assert obs.main(["diff", str(a), str(a), "-o", str(out)]) == 0
    assert json.loads(out.read_text())["ok"]
