"""GSPMD tensor-parallel sharding tests: the dp×tp annotated train step
must match the replicated single-device oracle, and the PartitionSpec
rules must actually shard heads/MLP-hidden over the model axis."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.parallel.sharding import (
    make_gspmd_train_step,
    transformer_param_spec,
)


@pytest.fixture(scope="module")
def dp_tp_mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.array(devs[:8]).reshape(4, 2), ("data", "model"))


def make_lm_and_data(seed=0):
    lm = TransformerLM(
        vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        max_len=16, dtype=jnp.float32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (8, 16), 0, 64)
    params = lm.init(jax.random.PRNGKey(seed + 1), tokens)
    return lm, tokens, params


def lm_loss_fn(lm):
    def loss(params, batch):
        logits = lm.apply(params, batch)
        targets = jnp.roll(batch, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    return loss


def test_param_spec_shards_heads_and_ff():
    lm, tokens, params = make_lm_and_data()
    spec = transformer_param_spec(params["params"])
    flat = jax.tree_util.tree_flatten_with_path(
        spec, is_leaf=lambda x: isinstance(x, P)
    )[0]
    by_path = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in flat
    }
    qkv = [s for p, s in by_path.items() if p.endswith("query/kernel")]
    assert qkv and all(s == P(None, "model", None) for s in qkv)
    wi = [s for p, s in by_path.items() if p.endswith("wi/kernel")]
    assert wi and all(s == P(None, "model") for s in wi)
    wo = [s for p, s in by_path.items() if p.endswith("wo/kernel")]
    assert wo and all(s == P("model", None) for s in wo)
    # Embeddings/norms replicated.
    emb = [s for p, s in by_path.items() if "embed" in p]
    assert emb and all(s == P() for s in emb)


@pytest.mark.slow
def test_gspmd_step_matches_replicated_oracle(dp_tp_mesh):
    lm, tokens, params = make_lm_and_data()
    loss_fn = lm_loss_fn(lm)
    optimizer = optax.adam(1e-2)

    spec = {"params": transformer_param_spec(params["params"])}
    step, shard_fn = make_gspmd_train_step(
        loss_fn, optimizer, dp_tp_mesh, spec, data_axis="data"
    )
    # The jitted step donates its inputs and device_put may alias on CPU;
    # keep independent copies for the oracle.
    rp = jax.tree.map(jnp.array, params)
    ro = optimizer.init(rp)
    sp, so = shard_fn(params, optimizer.init(params))
    for _ in range(3):
        sp, so, s_loss = step(sp, so, tokens)
        loss, grads = jax.value_and_grad(loss_fn)(rp, tokens)
        updates, ro = optimizer.update(grads, ro, rp)
        rp = optax.apply_updates(rp, updates)

    np.testing.assert_allclose(float(s_loss), float(loss), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(sp), jax.tree.leaves(rp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_gspmd_shards_optimizer_state(dp_tp_mesh):
    """Adam moments must ride their parameter's sharding (TP divides
    optimizer memory, the point of the shape-association rule)."""
    lm, tokens, params = make_lm_and_data()
    optimizer = optax.adam(1e-2)
    spec = {"params": transformer_param_spec(params["params"])}
    _, shard_fn = make_gspmd_train_step(
        lm_loss_fn(lm), optimizer, dp_tp_mesh, spec, data_axis="data"
    )
    sp, so = shard_fn(params, optimizer.init(params))

    # Find a head-sharded param (query kernel) and check its mu moment.
    flat_p = jax.tree_util.tree_flatten_with_path(sp)[0]
    q = [l for path, l in flat_p if "query" in str(path)][0]
    assert any(
        axis == "model"
        for entry in q.sharding.spec
        for axis in ((entry,) if isinstance(entry, str) else (entry or ()))
    )
    mu = so[0].mu if hasattr(so[0], "mu") else None
    assert mu is not None
    flat_mu = jax.tree_util.tree_flatten_with_path(mu)[0]
    q_mu = [l for path, l in flat_mu if "query" in str(path)][0]
    assert q_mu.sharding == q.sharding


def test_param_spec_rejects_unmatched_naming():
    """A model whose parameter names match none of the TP rules must
    raise, not silently replicate everything (TP doing nothing)."""
    foreign = {
        "dense_a": {"weight": jnp.zeros((8, 8))},
        "dense_b": {"weight": jnp.zeros((8, 8))},
    }
    with pytest.raises(ValueError, match="matched NO shardable"):
        transformer_param_spec(foreign)


# ---------------------------------------------------------------------------
# Vocab-parallel embedding + cross-entropy (VERDICT r4 item 6)
# ---------------------------------------------------------------------------


# Version-compat wrapper: forwards check_vma under whichever
# replication-check kwarg spelling this jax accepts.
from chainermn_tpu.communicators.base import shard_map_compat as shard_map


@pytest.fixture(scope="module")
def tp_mesh():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    return Mesh(np.array(devs[:4]), ("model",))


def test_vocab_parallel_embed_matches_take(tp_mesh):
    from chainermn_tpu.parallel.sharding import vocab_parallel_embed

    V, D = 64, 16
    emb = jax.random.normal(jax.random.PRNGKey(0), (V, D))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, V)

    f = jax.jit(shard_map(
        lambda t, e: vocab_parallel_embed(t, e, "model"),
        mesh=tp_mesh, in_specs=(P(), P("model")), out_specs=P(),
        check_vma=False,
    ))
    out = f(toks, emb)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jnp.take(emb, toks, axis=0)),
        rtol=1e-6,
    )


def test_vocab_parallel_embed_grad_matches(tp_mesh):
    from chainermn_tpu.parallel.sharding import vocab_parallel_embed

    V, D = 64, 16
    emb = jax.random.normal(jax.random.PRNGKey(0), (V, D))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, V)
    w = jax.random.normal(jax.random.PRNGKey(2), (2, 12, D))

    # Grad taken INSIDE the sharded region (the op's contract — its
    # backward scatters each device's cotangent into its own rows).
    f = jax.jit(shard_map(
        lambda t, e, w: jax.grad(
            lambda e: jnp.sum(vocab_parallel_embed(t, e, "model") * w)
        )(e),
        mesh=tp_mesh, in_specs=(P(), P("model"), P()),
        out_specs=P("model"),
        check_vma=False,
    ))
    g1 = f(toks, emb, w)

    def ref_loss(emb):
        return jnp.sum(jnp.take(emb, toks, axis=0) * w)

    g2 = jax.grad(ref_loss)(emb)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("neg_frac", [0.0, 0.3])
def test_vocab_parallel_ce_matches_fused(tp_mesh, neg_frac):
    """Trajectory equality: the vocab-sharded CE must equal the unsharded
    fused CE (same chunking, same bf16 matmul precision) in value and in
    both gradients."""
    from chainermn_tpu.ops.fused_ce import fused_cross_entropy
    from chainermn_tpu.parallel.sharding import vocab_parallel_cross_entropy

    N, D, V = 48, 16, 64
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(N, D).astype(np.float32))
    emb = jnp.asarray(rng.randn(V, D).astype(np.float32) * 0.1)
    lab = rng.randint(0, V, size=N)
    if neg_frac:
        lab[rng.rand(N) < neg_frac] = -1
    lab = jnp.asarray(lab, jnp.int32)

    # Gradients are taken INSIDE the sharded region (the op's contract,
    # like every explicit-collective device-plane op: the custom backward
    # issues its own psum, so each device seeds cotangent 1 and receives
    # the replicated dh / its local dE shard directly).
    def tp_value_and_grads(h, emb):
        f = shard_map(
            lambda h, e, l: jax.value_and_grad(
                lambda h, e: vocab_parallel_cross_entropy(
                    h, e, l, "model", chunk=16
                ), argnums=(0, 1),
            )(h, e),
            mesh=tp_mesh, in_specs=(P(), P("model"), P()),
            out_specs=(P(), (P(), P("model"))),
            check_vma=False,
        )
        return f(h, emb, lab)

    def ref_loss(h, emb):
        return fused_cross_entropy(h, emb, lab, chunk=16)

    loss, g1 = jax.jit(tp_value_and_grads)(h, emb)
    np.testing.assert_allclose(
        float(loss), float(ref_loss(h, emb)), rtol=2e-3
    )
    g2 = jax.grad(ref_loss, argnums=(0, 1))(h, emb)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=2e-3
        )


def test_vocab_parallel_ce_no_full_logits_per_device(tp_mesh):
    """The TP memory claim: inside the sharded region, no intermediate
    carries a full-vocab axis — every logit-like array is at most
    (chunk, V/n) per device."""
    from chainermn_tpu.parallel.sharding import vocab_parallel_cross_entropy

    N, D, V, chunk = 1024, 8, 256, 32
    n_shards = 4
    h = jnp.zeros((N, D), jnp.bfloat16)
    emb = jnp.zeros((V, D), jnp.float32)
    lab = jnp.zeros((N,), jnp.int32)

    f = shard_map(
        lambda h, e, l: jax.grad(
            lambda h, e: vocab_parallel_cross_entropy(
                h, e, l, "model", chunk=chunk
            ), argnums=(0, 1),
        )(h, e),
        mesh=tp_mesh, in_specs=(P(), P("model"), P()),
        out_specs=(P(), P("model")),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(f)(h, emb, lab)

    v_loc = V // n_shards
    biggest_rows = 0
    has_vocab_axis = False

    def walk(jx):
        nonlocal biggest_rows, has_vocab_axis
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                if len(shape) >= 2 and shape[-1] in (v_loc, V):
                    if shape[-1] == V and shape[-2] > 1:
                        has_vocab_axis = True
                    if shape[-1] == v_loc:
                        biggest_rows = max(
                            biggest_rows, int(np.prod(shape[:-1]))
                        )
            for p in eqn.params.values():
                sub = p.jaxpr if hasattr(p, "jaxpr") else p
                if hasattr(sub, "eqns"):
                    walk(sub)

    walk(jaxpr.jaxpr)
    assert biggest_rows <= chunk, biggest_rows
    assert not has_vocab_axis, "a full-vocab intermediate exists"


def test_vocab_parallel_embed_grad_reduce_sliced_cotangents(tp_mesh):
    """The SP-composed contract (grad_reduce=True): downstream consumes
    only a per-device sequence slice, so table cotangents arrive
    device-varying; each shard must still collect EVERY position's
    contribution to its rows (cotangent-psum-then-scatter).  Exact
    equality vs the dense take() oracle."""
    from chainermn_tpu.parallel.sharding import vocab_parallel_embed

    n = 4
    V, D, B, S = 64, 16, 2, 16
    S_loc = S // n
    emb = jax.random.normal(jax.random.PRNGKey(0), (V, D))
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)
    w = jax.random.normal(jax.random.PRNGKey(2), (B, S, D))

    def body(toks, emb, w):
        my = jax.lax.axis_index("model")

        def local_loss(emb):
            x_f = vocab_parallel_embed(toks, emb, "model", True)
            x_l = jax.lax.dynamic_slice_in_dim(x_f, my * S_loc, S_loc, 1)
            w_l = jax.lax.dynamic_slice_in_dim(w, my * S_loc, S_loc, 1)
            return jnp.sum(x_l * w_l)

        return jax.grad(local_loss)(emb)

    g1 = jax.jit(shard_map(
        body, mesh=tp_mesh, in_specs=(P(), P("model"), P()),
        out_specs=P("model"),
        check_vma=False,
    ))(toks, emb, w)

    g2 = jax.grad(lambda e: jnp.sum(jnp.take(e, toks, axis=0) * w))(emb)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5,
                               atol=1e-6)


def test_gather_seq_for_replicated_head_grad_is_1x(tp_mesh):
    """The head-gather's backward slices the replicated cotangent —
    upstream gradients come back exactly 1x (a plain all_gather's
    reduce-scatter transpose would inflate them by the axis size)."""
    from chainermn_tpu.parallel.sharding import (
        gather_seq_for_replicated_head,
    )

    n = 4
    B, S, D = 2, 16, 8
    S_loc = S // n
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, D))
    w = jax.random.normal(jax.random.PRNGKey(1), (B, S, D))

    def body(x, w):
        my = jax.lax.axis_index("model")
        x_l = jax.lax.dynamic_slice_in_dim(x, my * S_loc, S_loc, 1)

        def local_loss(x_l):
            # A replicated-gradient head: every device computes the same
            # loss from the gathered tensor.
            x_f = gather_seq_for_replicated_head(x_l, "model", 1)
            return jnp.sum(x_f * w)

        g_l = jax.grad(local_loss)(x_l)
        # Reassemble per-device slices for comparison.
        return jax.lax.all_gather(g_l, "model", axis=1, tiled=True)

    g1 = jax.jit(shard_map(
        body, mesh=tp_mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False,
    ))(x, w)
    g2 = jax.grad(lambda x: jnp.sum(x * w))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-6)


def test_sp_vocab_tp_end_to_end_grads_match(tp_mesh):
    """The full --vocab-tp composition on a miniature model: sharded
    embed -> slice to sequence shard -> (stand-in transformer layer) ->
    head-gather -> vocab-parallel CE.  Table AND layer gradients must
    match the dense end-to-end oracle exactly (not just track its loss
    trajectory)."""
    from chainermn_tpu.ops.fused_ce import fused_cross_entropy
    from chainermn_tpu.parallel.sharding import (
        gather_seq_for_replicated_head,
        vocab_parallel_cross_entropy,
        vocab_parallel_embed,
    )

    n = 4
    V, D, B, S = 64, 16, 2, 16
    S_loc = S // n
    emb = jax.random.normal(jax.random.PRNGKey(0), (V, D)) * 0.3
    wlayer = jax.random.normal(jax.random.PRNGKey(1), (D, D)) * 0.3
    toks = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, V)
    labels = jax.random.randint(jax.random.PRNGKey(3), (B, S), 0, V)

    def body(toks, labels, emb, wlayer):
        my = jax.lax.axis_index("model")

        def local_loss(emb, wlayer):
            x_f = vocab_parallel_embed(toks, emb, "model", True)
            x_l = jax.lax.dynamic_slice_in_dim(x_f, my * S_loc, S_loc, 1)
            h_l = jnp.tanh(x_l @ wlayer)
            h_f = gather_seq_for_replicated_head(h_l, "model", 1)
            return vocab_parallel_cross_entropy(
                h_f, emb, labels, "model", chunk=8
            )

        loss, (ge, gw) = jax.value_and_grad(
            local_loss, argnums=(0, 1)
        )(emb, wlayer)
        # Layer grads are per-sequence-shard partials: psum completes.
        return loss, ge, jax.lax.psum(gw, "model")

    loss, ge, gw = jax.jit(shard_map(
        body, mesh=tp_mesh,
        in_specs=(P(), P(), P("model"), P()),
        out_specs=(P(), P("model"), P()),
        check_vma=False,
    ))(toks, labels, emb, wlayer)

    def ref_loss(emb, wlayer):
        x = jnp.take(emb, toks, axis=0)
        h = jnp.tanh(x @ wlayer)
        return fused_cross_entropy(h, emb, labels, chunk=8)

    ref_l, (ref_ge, ref_gw) = jax.value_and_grad(
        ref_loss, argnums=(0, 1)
    )(emb, wlayer)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-3)
    np.testing.assert_allclose(np.asarray(ge), np.asarray(ref_ge),
                               rtol=5e-2, atol=2e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ref_gw),
                               rtol=5e-2, atol=2e-3)


def test_gspmd_moments_follow_path_not_shape(dp_tp_mesh):
    """Two SAME-shape params with DIFFERENT shardings: each moment must
    ride its own parameter's sharding via the tree-path association (a
    shape-keyed first-match-wins lookup mis-shards one of them), and
    scalar state (adam's count) stays replicated."""
    from jax.sharding import NamedSharding

    params = {
        "a": {"kernel": jnp.ones((16, 16))},
        "b": {"kernel": jnp.ones((16, 16))},
    }
    spec = {
        "a": {"kernel": P("model", None)},
        "b": {"kernel": P(None, "model")},
    }
    optimizer = optax.adam(1e-2)

    def loss_fn(p, batch):
        return jnp.sum((batch @ p["a"]["kernel"] @ p["b"]["kernel"]) ** 2)

    _, shard_fn = make_gspmd_train_step(
        loss_fn, optimizer, dp_tp_mesh, spec, data_axis="data"
    )
    sp, so = shard_fn(params, optimizer.init(params))
    for moment in (so[0].mu, so[0].nu):
        assert moment["a"]["kernel"].sharding == sp["a"]["kernel"].sharding
        assert moment["b"]["kernel"].sharding == sp["b"]["kernel"].sharding
        assert (moment["a"]["kernel"].sharding
                != moment["b"]["kernel"].sharding)
    assert so[0].count.sharding == NamedSharding(dp_tp_mesh, P())
