"""GSPMD tensor-parallel sharding tests: the dp×tp annotated train step
must match the replicated single-device oracle, and the PartitionSpec
rules must actually shard heads/MLP-hidden over the model axis."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.parallel.sharding import (
    make_gspmd_train_step,
    transformer_param_spec,
)


@pytest.fixture(scope="module")
def dp_tp_mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.array(devs[:8]).reshape(4, 2), ("data", "model"))


def make_lm_and_data(seed=0):
    lm = TransformerLM(
        vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
        max_len=16, dtype=jnp.float32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(seed), (8, 16), 0, 64)
    params = lm.init(jax.random.PRNGKey(seed + 1), tokens)
    return lm, tokens, params


def lm_loss_fn(lm):
    def loss(params, batch):
        logits = lm.apply(params, batch)
        targets = jnp.roll(batch, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    return loss


def test_param_spec_shards_heads_and_ff():
    lm, tokens, params = make_lm_and_data()
    spec = transformer_param_spec(params["params"])
    flat = jax.tree_util.tree_flatten_with_path(
        spec, is_leaf=lambda x: isinstance(x, P)
    )[0]
    by_path = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in flat
    }
    qkv = [s for p, s in by_path.items() if p.endswith("query/kernel")]
    assert qkv and all(s == P(None, "model", None) for s in qkv)
    wi = [s for p, s in by_path.items() if p.endswith("wi/kernel")]
    assert wi and all(s == P(None, "model") for s in wi)
    wo = [s for p, s in by_path.items() if p.endswith("wo/kernel")]
    assert wo and all(s == P("model", None) for s in wo)
    # Embeddings/norms replicated.
    emb = [s for p, s in by_path.items() if "embed" in p]
    assert emb and all(s == P() for s in emb)


@pytest.mark.slow
def test_gspmd_step_matches_replicated_oracle(dp_tp_mesh):
    lm, tokens, params = make_lm_and_data()
    loss_fn = lm_loss_fn(lm)
    optimizer = optax.adam(1e-2)

    spec = {"params": transformer_param_spec(params["params"])}
    step, shard_fn = make_gspmd_train_step(
        loss_fn, optimizer, dp_tp_mesh, spec, data_axis="data"
    )
    # The jitted step donates its inputs and device_put may alias on CPU;
    # keep independent copies for the oracle.
    rp = jax.tree.map(jnp.array, params)
    ro = optimizer.init(rp)
    sp, so = shard_fn(params, optimizer.init(params))
    for _ in range(3):
        sp, so, s_loss = step(sp, so, tokens)
        loss, grads = jax.value_and_grad(loss_fn)(rp, tokens)
        updates, ro = optimizer.update(grads, ro, rp)
        rp = optax.apply_updates(rp, updates)

    np.testing.assert_allclose(float(s_loss), float(loss), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(sp), jax.tree.leaves(rp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_gspmd_shards_optimizer_state(dp_tp_mesh):
    """Adam moments must ride their parameter's sharding (TP divides
    optimizer memory, the point of the shape-association rule)."""
    lm, tokens, params = make_lm_and_data()
    optimizer = optax.adam(1e-2)
    spec = {"params": transformer_param_spec(params["params"])}
    _, shard_fn = make_gspmd_train_step(
        lm_loss_fn(lm), optimizer, dp_tp_mesh, spec, data_axis="data"
    )
    sp, so = shard_fn(params, optimizer.init(params))

    # Find a head-sharded param (query kernel) and check its mu moment.
    flat_p = jax.tree_util.tree_flatten_with_path(sp)[0]
    q = [l for path, l in flat_p if "query" in str(path)][0]
    assert any(
        axis == "model"
        for entry in q.sharding.spec
        for axis in ((entry,) if isinstance(entry, str) else (entry or ()))
    )
    mu = so[0].mu if hasattr(so[0], "mu") else None
    assert mu is not None
    flat_mu = jax.tree_util.tree_flatten_with_path(mu)[0]
    q_mu = [l for path, l in flat_mu if "query" in str(path)][0]
    assert q_mu.sharding == q.sharding


def test_param_spec_rejects_unmatched_naming():
    """A model whose parameter names match none of the TP rules must
    raise, not silently replicate everything (TP doing nothing)."""
    foreign = {
        "dense_a": {"weight": jnp.zeros((8, 8))},
        "dense_b": {"weight": jnp.zeros((8, 8))},
    }
    with pytest.raises(ValueError, match="matched NO shardable"):
        transformer_param_spec(foreign)
