"""Worker for the SPMD construction-order divergence test.

Run as: python _mp_diverge_worker.py <pid> <nproc> <port> <mode>

Deliberately breaches the SPMD communicator-construction contract.  An
ORDINAL breach (the true correctness contract) must FAIL FAST with a
diagnostic; a mere construction-SITE difference must succeed with a
warning fingerprint.  (The round-2 design trusted the contract entirely:
a breach silently desynchronized every later send/recv/bcast key
namespace, delivering wrong payloads or hanging.)

mode "site":    both ranks build one communicator, but at different source
                lines.  The ordinal contract (the TRUE correctness
                requirement) holds, so the transfer must SUCCEED — with a
                RuntimeWarning fingerprinting the site mismatch on the
                non-root rank (ADVICE r3 #2: heterogeneous checkout paths
                or legal rank-conditional wrappers must not be fatal).
mode "ordinal": rank 1 builds an EXTRA communicator first, so its shared
                communicator has plane ordinal 2 while rank 0's has 1 →
                rank 1's first use times out waiting for rank 0's
                never-published plane-2 fingerprint and raises.

Prints "DIVERGE_OK <pid>" when the expected diagnostic fired.
"""

import os
import sys


def main():
    pid, nproc, port, mode = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    os.environ["CHAINERMN_TPU_PLANE_CHECK_TIMEOUT_MS"] = "3000"

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    from chainermn_tpu.communicators import create_communicator

    if mode == "site":
        import warnings

        if pid == 0:
            comm = create_communicator("naive")
        else:
            comm = create_communicator("naive")  # different line: site diverges
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            got = comm.bcast_obj({"x": 1}, root=0)
        # The ordinal contract holds → the transfer must succeed...
        assert got == {"x": 1}, got
        site_warns = [
            w for w in caught
            if "construction-site mismatch" in str(w.message)
        ]
        if pid == 0:
            # Rank 0 compares against itself and cannot see the breach.
            assert not site_warns, site_warns
        else:
            # ...but the non-root rank must fingerprint the mismatch.
            assert site_warns, (
                "non-root rank missed the site divergence warning"
            )
        print(f"DIVERGE_OK {pid}", flush=True)
        return

    if mode == "ordinal":
        if pid == 1:
            _extra = create_communicator("naive")  # rank-conditional!
        comm = create_communicator("naive")
        try:
            # Root-side bcast returns without waiting on peers, so rank 0
            # exits cleanly while rank 1's first use must raise: its
            # shared communicator has plane ordinal 2, which rank 0 never
            # constructed.
            comm.bcast_obj({"x": 1}, root=0)
        except RuntimeError as e:
            assert "construction order diverged" in str(e), e
            print(f"DIVERGE_OK {pid}", flush=True)
            return
        # Only rank 1 breached the contract; every OTHER rank's ordinal
        # matches rank 0's, so their bcast legitimately succeeds (root
        # returns without waiting; other receivers share rank 0's plane).
        assert pid != 1, "rank 1 missed the ordinal divergence"
        print(f"DIVERGE_OK {pid}", flush=True)
        return

    raise SystemExit(f"unknown mode {mode}")


if __name__ == "__main__":
    main()
