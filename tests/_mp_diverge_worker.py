"""Worker for the SPMD construction-order divergence test.

Run as: python _mp_diverge_worker.py <pid> <nproc> <port> <mode>

Deliberately breaches the SPMD communicator-construction contract and
expects the host plane to FAIL FAST with a diagnostic (the round-2 design
trusted the contract: a breach silently desynchronized every later
send/recv/bcast key namespace, delivering wrong payloads or hanging).

mode "site":    both ranks build one communicator, but at different source
                lines → construction-site mismatch raised at first use.
mode "ordinal": rank 1 builds an EXTRA communicator first, so its shared
                communicator has plane ordinal 2 while rank 0's has 1 →
                rank 1's first use times out waiting for rank 0's
                never-published plane-2 fingerprint and raises.

Prints "DIVERGE_OK <pid>" when the expected diagnostic fired.
"""

import os
import sys


def main():
    pid, nproc, port, mode = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    os.environ["CHAINERMN_TPU_PLANE_CHECK_TIMEOUT_MS"] = "3000"

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    from chainermn_tpu.communicators import create_communicator

    if mode == "site":
        if pid == 0:
            comm = create_communicator("naive")
        else:
            comm = create_communicator("naive")  # different line: site diverges
        try:
            comm.bcast_obj({"x": 1}, root=0)
        except RuntimeError as e:
            assert "construction-site mismatch" in str(e), e
            print(f"DIVERGE_OK {pid}", flush=True)
            return
        # Rank 0 compares against itself and cannot see the breach; any
        # OTHER rank must have raised.
        assert pid == 0, "non-root rank missed the site divergence"
        print(f"DIVERGE_OK {pid}", flush=True)
        return

    if mode == "ordinal":
        if pid == 1:
            _extra = create_communicator("naive")  # rank-conditional!
        comm = create_communicator("naive")
        try:
            # Root-side bcast returns without waiting on peers, so rank 0
            # exits cleanly while rank 1's first use must raise: its
            # shared communicator has plane ordinal 2, which rank 0 never
            # constructed.
            comm.bcast_obj({"x": 1}, root=0)
        except RuntimeError as e:
            assert "construction order diverged" in str(e), e
            print(f"DIVERGE_OK {pid}", flush=True)
            return
        assert pid == 0, "rank 1 missed the ordinal divergence"
        print(f"DIVERGE_OK {pid}", flush=True)
        return

    raise SystemExit(f"unknown mode {mode}")


if __name__ == "__main__":
    main()
