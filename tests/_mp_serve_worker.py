"""Worker for the multi-process serving soak test.

Run as: python _mp_serve_worker.py <pid> <nproc> <port> <kill_after> \
            [flight_dir]

A REAL serving fleet under one jax.distributed coordinator: rank 0 runs
the service-loop router (:func:`service.run_router`), every other rank a
replica (:func:`service.run_replica`).  With ``kill_after > 0`` the
HIGHEST rank SIGKILLs itself after streaming that many tokens —
mid-request, sequences live in its page pool, no cleanup — and the
router must detect the death (socket EOF → PeerGone, or missed
heartbeats), re-place the orphaned requests on the survivor with their
committed token prefix, and still return every stream BIT-IDENTICAL to
a sequential single-engine oracle.  The survivor's page pool passes
``assert_consistent`` on clean stop (checked inside run_replica).

Rank 0 prints ``SERVE_SOAK_OK`` after verifying all streams; surviving
replicas print ``SERVE_REPLICA_OK <pid>``.  The killed rank's "output"
is its -9 exit status.

With a ``flight_dir`` argument every rank records its trace spans to a
crash-surviving flight file (``flight_<rank>.jsonl``) — the SIGKILLed
rank's stage spans survive on disk and the host test stitches them into
the router's root spans for the failover postmortem.

With the literal argument ``traffic`` instead of a flight dir, the
router drives a seeded heavy-tailed workload (serving.workload — MMPP
bursts, Zipf shared prefixes, mixed length buckets) under an SLO-wired
tracer: the SIGKILL lands at peak generated load, and rank 0
additionally asserts every ``slo/burn_rate/*`` gauge stayed below 1.0
before printing ``SERVE_TRAFFIC_OK burn_max=<x>``.

With the literal argument ``gossip`` the fleet (router + 3 replicas)
runs model-based speculative decode with chunked prefill, and the
workload arrives in two waves to exercise the cluster-global prefix
index: wave 1 seeds exactly one replica with a 3-page template prompt
(plus decoy prompts elsewhere) while rank 1 — the cold-start placement
favorite — SIGKILLs itself mid-stream, so the template's pages end up
on a survivor the router only knows about through gossiped digests;
wave 2 (held back via ``after_gids`` until wave 1 is done) sends
template-prefixed prompts the router has never placed, and they must
route to whichever survivor actually holds the template.  Rank 0
prints ``SERVE_GOSSIP_OK holder=<rank>`` before ``SERVE_SOAK_OK``.

With the literal argument ``longctx`` the fleet (router + 2 replicas)
exercises STREAMING prefix registration over the wire: a long document
chunk-prefills on the cold-start favorite, each completed slice's
pages registering in the prefix index immediately and their digests
riding the next load beat; a follower request sharing the document is
gated on that gossip view (``after_index_pages``), so it arrives while
the document is STILL MID-PREFILL and must route to the warm replica —
which the router only knows is warm through the gossiped partial
prefix.  Rank 0 prints ``SERVE_LONGCTX_OK holder=<rank>`` before
``SERVE_SOAK_OK``.

With the literal argument ``tpgroup`` the fleet runs TWO tensor-
parallel shard groups (router + 2 groups x 2 shard processes: leaders
at ranks 1 and 3, followers at 2 and 4) and the doomed process is a
*follower* shard: rank 2 SIGKILLs itself after replaying ``kill_after``
mirrored device steps — mid-stream, lockstep state live.  The leader's
next mirror fan-out (or beat poll) raises PeerGone, it exits its serve
loop, the router sees the GROUP die on the leader's event edge, and the
orphaned streams re-place on the survivor group — every stream still
bit-identical to the sequential oracle, the survivor leader's pool
passing assert_consistent on clean stop.  Rank 0 prints
``SERVE_TPGROUP_OK survivor=<leader>`` before ``SERVE_SOAK_OK``.

With the argument ``metrics:<dir>`` the default kill9 soak additionally
exercises the fleet observability plane over the wire: every request
carries a tenant id, the router serves its merged fleet view at a live
``/metrics`` endpoint (port written to ``<dir>/router_metrics_port``),
and a rank-0 background thread scrapes it throughout the run.  After
the streams verify, rank 0 asserts the scrape series: the SIGKILLed
replica's per-replica series were present while it lived and are GONE
from the final view, fleet counters stayed monotone on either side of
the one step-down where the dead snapshot left the merge, and the
per-tenant token counters survived the failover.  Prints
``SERVE_METRICS_OK scrapes=<n>`` before ``SERVE_SOAK_OK``.
"""

import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    kill_after = int(sys.argv[4])
    flight_dir = sys.argv[5] if len(sys.argv) > 5 else None
    metrics_dir = None
    if flight_dir and flight_dir.startswith("metrics:"):
        metrics_dir = flight_dir.split(":", 1)[1]
        flight_dir = None
    traffic = flight_dir == "traffic"
    gossip = flight_dir == "gossip"
    longctx = flight_dir == "longctx"
    tpgroup = flight_dir == "tpgroup"
    flight_path = None
    if flight_dir and not traffic and not gossip and not longctx \
            and not tpgroup:
        flight_path = os.path.join(flight_dir, f"flight_{pid}.jsonl")

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    # Force backend init NOW on every rank: the CPU client's global
    # topology exchange blocks until all processes join, and the router
    # rank would otherwise never touch jax before its oracle check.
    jax.devices()

    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import EngineConfig, InferenceEngine
    from chainermn_tpu.serving.cluster import service

    # The gossip soak runs the full speculative stack over the wire:
    # layer-truncated self-draft + chunked prefill, verified bit-exact
    # against the same factory's sequential oracle.
    extra_cfg = {"draft": "model", "prefill_chunk": 8} if gossip else {}
    if longctx:
        # Tiny chunks stretch the document's prefill across many steps
        # so the gated follower genuinely lands mid-prefill.
        extra_cfg = {"prefill_chunk": 4}

    def engine_factory():
        lm = TransformerLM(vocab=32, d_model=16, n_heads=2, d_ff=32,
                           n_layers=2, max_len=64)
        params = lm.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))
        return InferenceEngine(lm, params, EngineConfig(
            block_size=4, n_blocks=64, max_len=64, max_batch=2,
            **extra_cfg,
        ))

    if traffic:
        # Heavy-tailed generated load: the kill lands mid-burst, with
        # Zipf-shared prefix pages live in both replica pools.  Length
        # buckets are capped so prompt + output fits max_len=64.
        from chainermn_tpu.serving import TrafficSpec, workload

        spec = TrafficSpec(
            seed=5, requests=10, rate=200.0, burst=6.0, p_burst=0.3,
            prefix_len=8, templates=4,
            prompt_buckets=((4, 12, 0.7), (14, 20, 0.3)),
            output_buckets=((4, 8, 0.8), (10, 12, 0.2)),
            vocab=32,
        )
        arrivals = workload.generate(spec)
        prompts = [list(a.prompt) for a in arrivals]
        news = [a.max_new_tokens for a in arrivals]
    elif gossip:
        # Wave 1 (gids 0-5): one 3-page template prompt plus five decoy
        # prompts.  kill_after=6 < max_new=8 guarantees rank 1 (cold-
        # start favorite, so it owns gid 0) dies before the template
        # request can finish there — the adopting survivor re-prefills
        # it and registers the template pages, and only gossip can tell
        # the router which survivor that was.  Wave 2 (gids 6-7):
        # template-prefixed prompts, gated on wave 1 via after_gids.
        rng = np.random.default_rng(29)
        template = [int(t) for t in rng.integers(0, 32, size=12)]
        prompts = [template] + [
            [int(t) for t in rng.integers(0, 32, size=int(n))]
            for n in rng.integers(4, 11, size=5)
        ]
        news = [8] * 6
        prompts += [
            template + [int(t) for t in rng.integers(0, 32, size=6)]
            for _ in range(2)
        ]
        news += [6, 6]
    elif longctx:
        # One long document (10 pages, 10 prefill slices at chunk=4)
        # plus ONE doc-prefixed follower.  The follower is gated on the
        # gossiped partial-prefix view (after_index_pages=6, set on the
        # request below): it is released while the document is still
        # mid-prefill, and only the streamed page registrations — the
        # digests ride each load beat — can tell the router which
        # replica is warm.  Exactly one follower: a second would eat
        # queue/batch penalties on the busy warm replica and tie-break
        # away to the idle one.
        rng = np.random.default_rng(31)
        doc = [int(t) for t in rng.integers(0, 32, size=40)]
        prompts = [list(doc)]
        news = [6]
        prompts += [doc + [int(t) for t in rng.integers(0, 32, size=4)]]
        news += [5]
    else:
        rng = np.random.default_rng(13)
        prompts = [
            [int(t) for t in rng.integers(0, 32, size=int(n))]
            for n in rng.integers(4, 11, size=6)
        ]
        # Half the fleet's traffic shares a 2-page prefix: the kill
        # lands while refcounted/index-registered pages are live in the
        # victim's and survivor's pools, and the survivor's clean-stop
        # assert_consistent proves no page leaked or double-freed.
        shared = [int(t) for t in rng.integers(0, 32, size=8)]
        prompts = [shared + p if i % 2 == 0 else p
                   for i, p in enumerate(prompts)]
        news = [8] * len(prompts)

    if pid == 0:
        requests = [
            {"prompt": p, "max_new_tokens": n}
            for p, n in zip(prompts, news)
        ]
        if gossip:
            for r in requests[6:]:
                r["after_gids"] = list(range(6))
        if longctx:
            requests[1]["after_index_pages"] = 6
        metrics_port_file = None
        scrapes = []
        scraper = None
        stop_scraping = None
        if metrics_dir is not None:
            import threading
            import time
            import urllib.request

            for gid, r in enumerate(requests):
                r["tenant"] = f"t{gid % 2}"
            metrics_port_file = os.path.join(metrics_dir,
                                             "router_metrics_port")
            stop_scraping = threading.Event()

            def _scrape_loop():
                while not stop_scraping.is_set():
                    if os.path.exists(metrics_port_file):
                        break
                    time.sleep(0.05)
                else:
                    return
                with open(metrics_port_file) as f:
                    mport = int(f.read().strip())
                url = f"http://127.0.0.1:{mport}/metrics"
                while not stop_scraping.is_set():
                    try:
                        with urllib.request.urlopen(url, timeout=5) as rs:
                            scrapes.append(rs.read().decode())
                    except OSError:
                        pass
                    time.sleep(0.1)

            scraper = threading.Thread(target=_scrape_loop, daemon=True)
            scraper.start()
        reporter = slo = None
        if traffic:
            from chainermn_tpu.observability.reporter import Reporter
            from chainermn_tpu.observability.tracing import SLOConfig

            reporter = Reporter()
            # Router-visible stages; lenient targets sized for CPU
            # compile stalls — burn < 1.0 is the green-SLO assertion.
            slo = SLOConfig(targets={"request": 120.0,
                                     "placement": 60.0})
        # miss_after_s must tolerate a replica stalled in a cold jit
        # compile (seconds on CPU); REAL deaths are detected much
        # faster via socket EOF -> PeerGone on the event edge.
        results = service.run_router(
            nproc, requests, miss_after_s=30.0, timeout_s=180.0,
            flight_path=flight_path, reporter=reporter, slo=slo,
            metrics_port_file=metrics_port_file,
            group_size=2 if tpgroup else 1,
        )
        if scraper is not None:
            stop_scraping.set()
            scraper.join(timeout=10)
        try:
            oracle = engine_factory()
            failovers = 0
            for gid, (p, n) in enumerate(zip(prompts, news)):
                rr = results[gid]
                assert rr["status"] == "finished", (gid, rr)
                want = oracle.generate(p, n)
                assert rr["tokens"] == want, (gid, rr["tokens"], want)
                failovers += rr["failovers"]
            if kill_after > 0:
                assert failovers > 0, "nobody failed over despite kill"
            if tpgroup:
                # The follower-shard kill must have collapsed the WHOLE
                # group led by rank 1: every stream that failed over
                # finished on the survivor group's leader (rank 3), and
                # the survivor leader's clean-stop assert_consistent
                # (inside run_replica) proves its pool absorbed the
                # orphans without leaking a page.
                moved = [g for g, _ in enumerate(prompts)
                         if results[g]["failovers"] > 0]
                assert moved, results
                for g in moved:
                    assert results[g]["replica"] == 3, (g, results[g])
                print("SERVE_TPGROUP_OK survivor=3")
            if gossip:
                # The template request must have outlived rank 1's
                # SIGKILL on a survivor, and BOTH gated wave-2 requests
                # must have routed to that exact survivor — the router
                # never placed the template there itself, so only the
                # gossiped digest view can have told it.
                holder = results[0]["replica"]
                assert holder in (2, 3), results[0]
                routed = [results[6]["replica"], results[7]["replica"]]
                assert routed == [holder, holder], (holder, routed)
                print(f"SERVE_GOSSIP_OK holder={holder}")
            if longctx:
                # The follower was released by gossiped STREAMING page
                # registrations while the document was still prefilling
                # — it must have landed on the replica mid-prefill, not
                # the idle one (whose free/queue score would otherwise
                # win for a never-seen prompt).
                holder = results[0]["replica"]
                assert results[1]["replica"] == holder, results
                print(f"SERVE_LONGCTX_OK holder={holder}")
            if traffic:
                gauges = reporter.summary()["gauges"]
                burns = {
                    k.split("/", 2)[2]: g["value"]
                    for k, g in gauges.items()
                    if k.startswith("slo/burn_rate/")
                }
                assert burns, "no SLO burn gauges populated"
                burn_max = max(burns.values())
                assert burn_max < 1.0, f"SLO burned red: {burns}"
                print(f"SERVE_TRAFFIC_OK burn_max={burn_max:.4f}")
            if metrics_dir is not None:
                import re

                assert len(scrapes) >= 3, f"only {len(scrapes)} scrapes"
                dead = f'replica="{nproc - 1}"'
                lived = [i for i, s in enumerate(scrapes) if dead in s]
                assert lived, "dead replica's series never scraped alive"
                assert dead not in scrapes[-1], \
                    "dead replica's series survived its forget"
                # Per-tenant token accounting survived the failover: the
                # orphaned requests re-bill on the adopting survivor.
                ctr_re = re.compile(
                    r'chainermn_tpu_counter_total\{name="([^"]+)"\} (\S+)')
                final = {m.group(1): float(m.group(2))
                         for m in ctr_re.finditer(scrapes[-1])}
                for t in ("t0", "t1"):
                    for which in ("tokens_in", "tokens_out"):
                        name = f"tenant/{t}/{which}"
                        assert final.get(name, 0.0) > 0, (name, final)
                # Fleet counters are monotone except for the ONE step
                # where the dead replica's snapshot leaves the merge —
                # split there and each segment must be nondecreasing.
                cut = lived[-1] + 1
                for seg in (scrapes[:cut], scrapes[cut:]):
                    prev = {}
                    for s in seg:
                        cur = {m.group(1): float(m.group(2))
                               for m in ctr_re.finditer(s)}
                        for k, v in prev.items():
                            assert cur.get(k, 0.0) >= v, (k, v, cur.get(k))
                        prev = cur
                print(f"SERVE_METRICS_OK scrapes={len(scrapes)}")
        except BaseException:
            import traceback

            traceback.print_exc()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(1)  # don't hang in the atexit shutdown barrier
        print("SERVE_SOAK_OK")
        # Skip jax's atexit shutdown barrier: with a SIGKILLed rank in
        # the world it blocks until the coordination service aborts us.
        sys.stdout.flush()
        os._exit(0)

    # Replicas.  max_queue=3 forces the router to spread the burst over
    # both replicas (cold-start placement prefers the lowest rank until
    # its queue fills), so the doomed rank is guaranteed live work.  In
    # gossip mode the doomed rank is 1 — the cold-start favorite that
    # owns the template request — and max_queue=2 spreads wave 1 over
    # all three replicas.
    if tpgroup:
        # Two shard groups of 2: leaders 1 and 3, followers 2 and 4.
        # The doomed process is FOLLOWER rank 2 — it dies after
        # replaying kill_after mirrored steps, which must take down the
        # whole group led by rank 1.
        from chainermn_tpu.serving.cluster.shard_group import plan_groups

        group = next(g for g in plan_groups(nproc, 2, 1)
                     if pid in g.ranks)
        out = service.run_replica(
            pid, nproc, engine_factory, max_queue=3, group=group,
            kill_after_ops=kill_after if (kill_after > 0 and pid == 2)
            else None,
        )
        print(f"SERVE_REPLICA_OK {pid} {out['reason']}")
        sys.stdout.flush()
        os._exit(0)
    doomed = kill_after > 0 and pid == (1 if gossip else nproc - 1)
    out = service.run_replica(
        pid, nproc, engine_factory,
        max_queue=2 if gossip else 3,
        kill_after_tokens=kill_after if doomed else None,
        flight_path=flight_path,
        spec_tokens=2 if gossip else 0,
    )
    print(f"SERVE_REPLICA_OK {pid} {out['reason']}")
    sys.stdout.flush()
    os._exit(0)  # see rank 0: no shutdown barrier with a corpse in it


if __name__ == "__main__":
    main()
