"""Worker for the multi-process serving soak test.

Run as: python _mp_serve_worker.py <pid> <nproc> <port> <kill_after> \
            [flight_dir]

A REAL serving fleet under one jax.distributed coordinator: rank 0 runs
the service-loop router (:func:`service.run_router`), every other rank a
replica (:func:`service.run_replica`).  With ``kill_after > 0`` the
HIGHEST rank SIGKILLs itself after streaming that many tokens —
mid-request, sequences live in its page pool, no cleanup — and the
router must detect the death (socket EOF → PeerGone, or missed
heartbeats), re-place the orphaned requests on the survivor with their
committed token prefix, and still return every stream BIT-IDENTICAL to
a sequential single-engine oracle.  The survivor's page pool passes
``assert_consistent`` on clean stop (checked inside run_replica).

Rank 0 prints ``SERVE_SOAK_OK`` after verifying all streams; surviving
replicas print ``SERVE_REPLICA_OK <pid>``.  The killed rank's "output"
is its -9 exit status.

With a ``flight_dir`` argument every rank records its trace spans to a
crash-surviving flight file (``flight_<rank>.jsonl``) — the SIGKILLed
rank's stage spans survive on disk and the host test stitches them into
the router's root spans for the failover postmortem.

With the literal argument ``traffic`` instead of a flight dir, the
router drives a seeded heavy-tailed workload (serving.workload — MMPP
bursts, Zipf shared prefixes, mixed length buckets) under an SLO-wired
tracer: the SIGKILL lands at peak generated load, and rank 0
additionally asserts every ``slo/burn_rate/*`` gauge stayed below 1.0
before printing ``SERVE_TRAFFIC_OK burn_max=<x>``.
"""

import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    kill_after = int(sys.argv[4])
    flight_dir = sys.argv[5] if len(sys.argv) > 5 else None
    traffic = flight_dir == "traffic"
    flight_path = None
    if flight_dir and not traffic:
        flight_path = os.path.join(flight_dir, f"flight_{pid}.jsonl")

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    # Force backend init NOW on every rank: the CPU client's global
    # topology exchange blocks until all processes join, and the router
    # rank would otherwise never touch jax before its oracle check.
    jax.devices()

    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving import EngineConfig, InferenceEngine
    from chainermn_tpu.serving.cluster import service

    def engine_factory():
        lm = TransformerLM(vocab=32, d_model=16, n_heads=2, d_ff=32,
                           n_layers=2, max_len=64)
        params = lm.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 8), jnp.int32))
        return InferenceEngine(lm, params, EngineConfig(
            block_size=4, n_blocks=64, max_len=64, max_batch=2,
        ))

    if traffic:
        # Heavy-tailed generated load: the kill lands mid-burst, with
        # Zipf-shared prefix pages live in both replica pools.  Length
        # buckets are capped so prompt + output fits max_len=64.
        from chainermn_tpu.serving import TrafficSpec, workload

        spec = TrafficSpec(
            seed=5, requests=10, rate=200.0, burst=6.0, p_burst=0.3,
            prefix_len=8, templates=4,
            prompt_buckets=((4, 12, 0.7), (14, 20, 0.3)),
            output_buckets=((4, 8, 0.8), (10, 12, 0.2)),
            vocab=32,
        )
        arrivals = workload.generate(spec)
        prompts = [list(a.prompt) for a in arrivals]
        news = [a.max_new_tokens for a in arrivals]
    else:
        rng = np.random.default_rng(13)
        prompts = [
            [int(t) for t in rng.integers(0, 32, size=int(n))]
            for n in rng.integers(4, 11, size=6)
        ]
        # Half the fleet's traffic shares a 2-page prefix: the kill
        # lands while refcounted/index-registered pages are live in the
        # victim's and survivor's pools, and the survivor's clean-stop
        # assert_consistent proves no page leaked or double-freed.
        shared = [int(t) for t in rng.integers(0, 32, size=8)]
        prompts = [shared + p if i % 2 == 0 else p
                   for i, p in enumerate(prompts)]
        news = [8] * len(prompts)

    if pid == 0:
        requests = [
            {"prompt": p, "max_new_tokens": n}
            for p, n in zip(prompts, news)
        ]
        reporter = slo = None
        if traffic:
            from chainermn_tpu.observability.reporter import Reporter
            from chainermn_tpu.observability.tracing import SLOConfig

            reporter = Reporter()
            # Router-visible stages; lenient targets sized for CPU
            # compile stalls — burn < 1.0 is the green-SLO assertion.
            slo = SLOConfig(targets={"request": 120.0,
                                     "placement": 60.0})
        # miss_after_s must tolerate a replica stalled in a cold jit
        # compile (seconds on CPU); REAL deaths are detected much
        # faster via socket EOF -> PeerGone on the event edge.
        results = service.run_router(
            nproc, requests, miss_after_s=30.0, timeout_s=180.0,
            flight_path=flight_path, reporter=reporter, slo=slo,
        )
        try:
            oracle = engine_factory()
            failovers = 0
            for gid, (p, n) in enumerate(zip(prompts, news)):
                rr = results[gid]
                assert rr["status"] == "finished", (gid, rr)
                want = oracle.generate(p, n)
                assert rr["tokens"] == want, (gid, rr["tokens"], want)
                failovers += rr["failovers"]
            if kill_after > 0:
                assert failovers > 0, "nobody failed over despite kill"
            if traffic:
                gauges = reporter.summary()["gauges"]
                burns = {
                    k.split("/", 2)[2]: g["value"]
                    for k, g in gauges.items()
                    if k.startswith("slo/burn_rate/")
                }
                assert burns, "no SLO burn gauges populated"
                burn_max = max(burns.values())
                assert burn_max < 1.0, f"SLO burned red: {burns}"
                print(f"SERVE_TRAFFIC_OK burn_max={burn_max:.4f}")
        except BaseException:
            import traceback

            traceback.print_exc()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(1)  # don't hang in the atexit shutdown barrier
        print("SERVE_SOAK_OK")
        # Skip jax's atexit shutdown barrier: with a SIGKILLed rank in
        # the world it blocks until the coordination service aborts us.
        sys.stdout.flush()
        os._exit(0)

    # Replicas.  max_queue=3 forces the router to spread the burst over
    # both replicas (cold-start placement prefers the lowest rank until
    # its queue fills), so the doomed rank is guaranteed live work.
    doomed = kill_after > 0 and pid == nproc - 1
    out = service.run_replica(
        pid, nproc, engine_factory, max_queue=3,
        kill_after_tokens=kill_after if doomed else None,
        flight_path=flight_path,
    )
    print(f"SERVE_REPLICA_OK {pid} {out['reason']}")
    sys.stdout.flush()
    os._exit(0)  # see rank 0: no shutdown barrier with a corpse in it


if __name__ == "__main__":
    main()
