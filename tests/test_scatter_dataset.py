"""scatter_dataset tests mirroring the reference's
tests/datasets_tests/test_scatter_dataset.py (SURVEY §4): coverage of all
indices, ±1-equal chunk sizes, shuffle reproducibility with a seed."""

import numpy as np
import pytest

from chainermn_tpu.communicators import create_communicator
from chainermn_tpu.datasets import (
    SubDataset,
    create_empty_dataset,
    scatter_dataset,
    scatter_index,
)


class _FakeComm:
    """Stub communicator pinning rank/size — the reference's dummy
    communicator trick for unit-testing wrapper logic without transport."""

    def __init__(self, rank, size):
        self.rank = rank
        self.size = size

    def bcast_obj(self, obj, root=0):
        return obj


@pytest.mark.parametrize("n", [10, 16, 17, 101])
@pytest.mark.parametrize("size", [1, 2, 3, 8])
def test_partition_covers_all_indices(n, size):
    chunks = [scatter_index(n, _FakeComm(r, size)) for r in range(size)]
    allidx = np.concatenate(chunks)
    assert sorted(allidx.tolist()) == list(range(n))
    lens = [len(c) for c in chunks]
    assert max(lens) - min(lens) <= 1
    assert lens == sorted(lens, reverse=True)  # earlier ranks get longer chunks


def test_seeded_shuffle_is_reproducible():
    a = scatter_index(100, _FakeComm(1, 4), shuffle=True, seed=7)
    b = scatter_index(100, _FakeComm(1, 4), shuffle=True, seed=7)
    np.testing.assert_array_equal(a, b)
    c = scatter_index(100, _FakeComm(1, 4), shuffle=True, seed=8)
    assert not np.array_equal(a, c)


def test_shuffle_partitions_globally():
    size = 4
    chunks = [
        scatter_index(103, _FakeComm(r, size), shuffle=True, seed=3)
        for r in range(size)
    ]
    allidx = np.concatenate(chunks)
    assert sorted(allidx.tolist()) == list(range(103))


def test_force_equal_length_pads_by_wrapping():
    data = list(range(10))
    shards = [
        scatter_dataset(data, _FakeComm(r, 4), force_equal_length=True)
        for r in range(4)
    ]
    assert all(len(s) == 3 for s in shards)
    seen = set()
    for s in shards:
        seen.update(s.indices.tolist())
    assert seen == set(range(10))


def test_subdataset_getitem():
    ds = SubDataset([10, 11, 12, 13], np.array([2, 0]))
    assert ds[0] == 12 and ds[1] == 10
    assert len(ds) == 2
    assert ds[0:2] == [12, 10]


def test_real_communicator_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    shard = scatter_dataset(list(range(50)), comm, shuffle=True, seed=0)
    assert len(shard) == 50  # single process holds everything


def test_create_empty_dataset():
    ds = create_empty_dataset(list(range(7)))
    assert len(ds) == 7
    assert ds[3] == ()
