"""Test harness: force an 8-device virtual CPU mesh.

The reference's CI trick (SURVEY §4) is ``mpiexec -n 2 pytest`` on one box —
real SPMD over shared-memory MPI.  The TPU-native analogue is
``--xla_force_host_platform_device_count=8`` on the CPU platform: one
process, eight virtual devices, every collective exercised for real through
``shard_map``.

Note on this container: its sitecustomize registers the axon TPU PJRT
plugin and sets ``jax_platforms="axon,cpu"`` via ``jax.config`` at
interpreter start, which beats any later environment variable.  Overriding
through ``jax.config.update`` here (before the first backend
initialization) reliably lands the suite on the virtual CPU mesh.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's wall time is dominated by
# hundreds of small jit compiles; warm re-runs hit the cache instead.
_cache_dir = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_compilation_cache"
)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import pytest  # noqa: E402

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Where a telemetry file accidentally written with a relative path would
# land during the suite (tests run with cwd = repo root).
_LEAK_SCAN_DIRS = (
    _REPO_ROOT,
    os.path.join(_REPO_ROOT, "tests"),
    os.path.join(_REPO_ROOT, "examples"),
    os.path.join(_REPO_ROOT, "benchmarks"),
)
_LEAK_PATTERNS = (".jsonl", ".prom")


def _telemetry_files():
    found = set()
    for d in _LEAK_SCAN_DIRS:
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for n in names:
            if n.endswith(_LEAK_PATTERNS) or ".jsonl." in n:
                found.add(os.path.join(d, n))
    return found


@pytest.fixture(autouse=True)
def _no_telemetry_leaks():
    """Fail any test that leaves a step log / Prometheus export outside
    tmp: StepRecorder paths in tests must go through tmp_path.  (Scan is
    non-recursive over the repo root and the dirs tests use as cwd —
    cheap enough to run autouse.)"""
    before = _telemetry_files()
    yield
    leaked = _telemetry_files() - before
    assert not leaked, (
        "test leaked telemetry files into the repo (write them under "
        f"tmp_path instead): {sorted(leaked)}"
    )


def pytest_collection_modifyitems(config, items):
    """Soak tests (long recorder/rotation runs) stay out of tier-1: any
    test with 'soak' in its name gets the ``slow`` marker implicitly, so
    forgetting the decorator cannot slow the gate."""
    for item in items:
        if "soak" in item.name:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


@pytest.fixture(scope="session", params=[(1, 8), (2, 4), (4, 2)])
def mesh(request, devices8):
    """Meshes factoring 8 devices into (inter, intra) shapes, exercising the
    single-host and simulated multi-host topologies."""
    from chainermn_tpu.communicators import build_mesh

    inter, intra = request.param
    return build_mesh(inter_size=inter, intra_size=intra, devices=devices8)


@pytest.fixture
def lint_clean():
    """The static collective linter's assertion surface
    (docs/static_analysis.md): ``lint_clean(step, params, state, batch,
    comm=comm)`` raises ``LintError`` with the full report when any rule
    R001–R005 flags the step."""
    from chainermn_tpu.analysis import assert_lint_clean

    return assert_lint_clean


def subprocess_env(n_devices: int = 8) -> dict:
    """Environment for spawning REAL worker/example subprocesses on the
    virtual CPU mesh: scrub the axon TPU plugin trigger, force the CPU
    platform, and put the repo root on PYTHONPATH so the in-repo package
    imports without an installed wheel."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    return env
