"""Two-process jax.distributed integration test — the TPU-native analogue
of the reference's ``mpiexec -n 2 pytest`` CI trick (SURVEY §4): REAL
process boundaries, the coordinator standing in for MPI's control plane.
Exercises the cross-process object plane (bcast/gather/allreduce_obj),
host-plane p2p (send_obj/recv_obj over the KV store, incl. multi-chunk
payloads), barrier, dataset scattering, parameter broadcast, the
communicator × wire-dtype matrix, and a cross-process ZeRO-3 step."""

import os
import socket
import subprocess
import sys

import pytest

from conftest import subprocess_env

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_object_plane(tmp_path):
    port = _free_port()
    nproc = 2
    env = subprocess_env(n_devices=1)
    # Shared dir for the multi-host checkpointer round-trip in the worker.
    env["CHAINERMN_TPU_TEST_CKPT_DIR"] = str(tmp_path)

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multiprocess workers timed out:\n" + "\n".join(outs))

    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_WORKER_OK {i}" in out, f"worker {i} output:\n{out}"


_DIVERGE_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_diverge_worker.py"
)


@pytest.mark.parametrize("mode", ["site", "ordinal"])
def test_construction_order_divergence_fails_fast(mode):
    """A rank-conditional create_communicator (breaching the SPMD
    construction contract the host plane's key namespaces rely on) must
    fail FAST with a diagnostic, not hang or deliver mixed-up payloads."""
    port = _free_port()
    env = subprocess_env(n_devices=1)
    procs = [
        subprocess.Popen(
            [sys.executable, _DIVERGE_WORKER, str(i), "2", str(port), mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(
            "divergence was not detected (workers hung):\n" + "\n".join(outs)
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"DIVERGE_OK {i}" in out, f"worker {i} output:\n{out}"


_RESUME_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_resume_worker.py"
)


def _run_resume_workers(ckpt_dir, crash_after, timeout=420, nproc=2):
    port = _free_port()
    env = subprocess_env(n_devices=2)
    procs = [
        subprocess.Popen(
            [sys.executable, _RESUME_WORKER, str(i), str(nproc), str(port),
             str(ckpt_dir), str(crash_after)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("resume workers timed out:\n" + "\n".join(outs))
    return procs, outs


def _digest(outs):
    import re

    for out in outs:
        m = re.search(r"params_digest ([0-9a-f]{8})", out)
        if m:
            return m.group(1)
    pytest.fail("no params_digest in worker output:\n" + "\n".join(outs))


def test_kill9_and_resume_bit_identical(tmp_path):
    """End-to-end fault tolerance on the REAL imagenet example under a
    2-process jax.distributed world: SIGKILL both processes mid-epoch
    (after a consistent generation exists), relaunch the same command
    line, and the run must (a) resume from a saved iteration rather than
    restart, and (b) finish with parameters BIT-IDENTICAL to an
    uninterrupted oracle run."""
    import re

    # Oracle: uninterrupted run (8 global steps at this config).
    oracle_dir = tmp_path / "oracle"
    procs, outs = _run_resume_workers(oracle_dir, crash_after=0)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"oracle worker {i} failed:\n{out}"
    oracle = _digest(outs)

    # Crash run: both processes SIGKILL themselves once generation >= 5
    # is consistent on disk (mid-epoch-1: step 5 of 8).
    crash_dir = tmp_path / "crash"
    procs, outs = _run_resume_workers(crash_dir, crash_after=5)
    # At least one process dies by its own SIGKILL; the peer may either
    # also SIGKILL itself or crash out when the killed coordinator's
    # control plane vanishes under it (rc != 0 either way).
    codes = [p.returncode for p in procs]
    assert -9 in codes, f"no SIGKILL observed: {codes}\n" + "\n".join(outs)
    assert all(c != 0 for c in codes), (
        f"a worker exited cleanly in the crash phase: {codes}"
    )

    # Relaunch: must resume (not restart) and reproduce the oracle.
    procs, outs = _run_resume_workers(crash_dir, crash_after=0)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"resume worker {i} failed:\n{out}"
    m = re.search(r"resumed from iteration (\d+)", "\n".join(outs))
    assert m, "relaunch did not resume:\n" + "\n".join(outs)
    assert int(m.group(1)) >= 5
    assert _digest(outs) == oracle


_MODELPAR_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_modelpar_worker.py"
)


def _launch(worker, nproc, *extra, n_devices=4, timeout=420, env_extra=None):
    port = _free_port()
    env = subprocess_env(n_devices=1)
    env["CHAINERMN_TPU_TEST_LOCAL_DEVICES"] = str(n_devices)
    if env_extra:
        env.update(env_extra)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(nproc), str(port), *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("workers timed out:\n" + "\n".join(outs))
    return procs, outs


def test_two_process_model_parallelism(tmp_path):
    """VERDICT r4 item 3: pipeline schedules (fill-drain 1F1B, circular,
    interleaved), the heterogeneous links chain, zigzag SP, and the MoE
    all-to-all each run their collective leg over a REAL process boundary
    (the inter axis of a 2-process x 4-device mesh), checked against
    single-host oracles."""
    procs, outs = _launch(_MODELPAR_WORKER, nproc=2, n_devices=4)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_MODELPAR_OK {i}" in out, f"worker {i} output:\n{out}"


def test_four_process_object_plane(tmp_path):
    """The DP/object-plane matrix re-proven at 4 ranks x 2 local devices
    (the reference CI's n=2 shape, doubled): collectives, p2p, splits,
    the communicator x wire-dtype matrix, ZeRO-3, checkpointer."""
    procs, outs = _launch(
        _WORKER, nproc=4, n_devices=2, timeout=600,
        env_extra={"CHAINERMN_TPU_TEST_CKPT_DIR": str(tmp_path)},
    )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_WORKER_OK {i}" in out, f"worker {i} output:\n{out}"


def test_four_rank_construction_divergence_fails_fast():
    """Divergence detection re-proven at 4 ranks."""
    procs, outs = _launch(
        _DIVERGE_WORKER, 4, "ordinal", n_devices=1, timeout=180,
    )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"DIVERGE_OK {i}" in out, f"worker {i} output:\n{out}"


def test_kill9_and_resume_bit_identical_four_ranks(tmp_path):
    """Kill -9 fault tolerance re-proven at 4 ranks x 2 devices: crash
    mid-run, relaunch, resume, reproduce the uninterrupted 4-rank
    oracle's digest bit-for-bit."""
    import re

    oracle_dir = tmp_path / "oracle4"
    procs, outs = _run_resume_workers(oracle_dir, crash_after=0, nproc=4,
                                      timeout=600)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"oracle worker {i} failed:\n{out}"
    oracle = _digest(outs)

    crash_dir = tmp_path / "crash4"
    procs, outs = _run_resume_workers(crash_dir, crash_after=5, nproc=4,
                                      timeout=600)
    codes = [p.returncode for p in procs]
    assert -9 in codes, f"no SIGKILL observed: {codes}\n" + "\n".join(outs)
    assert all(c != 0 for c in codes), (
        f"a worker exited cleanly in the crash phase: {codes}"
    )

    procs, outs = _run_resume_workers(crash_dir, crash_after=0, nproc=4,
                                      timeout=600)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"resume worker {i} failed:\n{out}"
    m = re.search(r"resumed from iteration (\d+)", "\n".join(outs))
    assert m, "relaunch did not resume:\n" + "\n".join(outs)
    assert int(m.group(1)) >= 5
    assert _digest(outs) == oracle


_PEERGONE_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_peergone_worker.py"
)


def test_peer_death_mid_send_peergone_and_replacement():
    """Transport churn over REAL process boundaries: rank 1 dies by
    SIGKILL mid-frame; the survivor gets PeerGone inside its timeout
    (not a hang), accepts a same-rank replacement incarnation
    (endpoint republished through the real coordination-service KV),
    and keeps talking to an unrelated peer."""
    procs, outs = _launch(_PEERGONE_WORKER, nproc=3, n_devices=1,
                          timeout=300)
    codes = [p.returncode for p in procs]
    assert codes[1] == -9, f"rank 1 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    for i in (0, 2):
        assert codes[i] == 0, f"survivor {i} failed:\n{outs[i]}"
        assert f"MP_PEERGONE_OK {i}" in outs[i], outs[i]


_SERVE_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_serve_worker.py"
)


def test_serving_cluster_survives_replica_kill9(tmp_path):
    """The serving-fleet soak: router + 2 replica processes, the highest
    rank SIGKILLed mid-stream with live sequences in its pool.  Every
    request must still finish with a token stream bit-identical to the
    sequential single-engine oracle (failover re-prefills from the
    committed prefix), and the survivor's page pool must pass
    assert_consistent on clean stop.

    Every rank also records to a flight file; the postmortem below
    stitches the dead rank's on-disk spans into the router's root spans
    and requires a coherent story: no orphans, monotone timestamps, a
    failover event, and the resumed request showing work from BOTH the
    killed and the adopting replica."""
    procs, outs = _launch(_SERVE_WORKER, 3, "5", str(tmp_path),
                          n_devices=1, timeout=420)
    codes = [p.returncode for p in procs]
    assert codes[2] == -9, f"rank 2 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    assert codes[1] == 0, f"survivor replica failed:\n{outs[1]}"
    assert "SERVE_REPLICA_OK 1" in outs[1], outs[1]

    # -- flight-recorder postmortem ------------------------------------
    from chainermn_tpu.observability import tracing

    rows = tracing.read_flight_dir(str(tmp_path / "flight_*.jsonl"))
    assert rows, "no flight records survived"
    trees = tracing.stitch(rows)
    assert len(trees) == 6  # one trace per request, none lost
    crossed = []
    for tid, t in trees.items():
        v = tracing.validate_trace(t["spans"])
        # the SIGKILLed rank only ever wrote CLOSED spans parented to
        # the router-owned root: nothing may dangle, clocks line up
        assert not v["orphans"], (tid, v)
        assert v["connected"], (tid, v)
        assert v["monotone"], (tid, v)
        reps = {s.get("replica") for s in t["spans"]}
        if {1, 2} <= reps:
            crossed.append(tid)
    # at least one stream was cut on rank 2 and adopted by rank 1 —
    # its single trace carries both replicas' spans
    assert crossed, sorted(
        (tid, sorted(str(s.get("replica")) for s in t["spans"]))
        for tid, t in trees.items()
    )
    evts = [r for r in rows if r.get("event") == "evt"]
    assert any(r["name"] == "failover" for r in evts), evts


def test_serving_cluster_clean_run_no_kill():
    """Same fleet, nobody dies: all streams oracle-exact, zero
    failovers, both replicas stop cleanly."""
    procs, outs = _launch(_SERVE_WORKER, 3, "0", n_devices=1, timeout=420)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
    assert "SERVE_SOAK_OK" in outs[0]
    assert "SERVE_REPLICA_OK 1" in outs[1]
    assert "SERVE_REPLICA_OK 2" in outs[2]


def test_serving_tp_shard_group_survives_follower_kill9():
    """The shard-group soak: router + TWO tensor-parallel groups of 2
    processes each (leaders 1 and 3, followers 2 and 4), and the doomed
    process is a FOLLOWER — rank 2 SIGKILLs itself after replaying 4
    mirrored device steps, mid-stream, lockstep mirrors live.  The
    leader must detect the dead shard (PeerGone on the mirror fan-out
    or beat poll) and exit, the router must fail the WHOLE group on the
    leader's event edge, and the orphaned streams must re-place on the
    survivor group — every stream bit-identical to the sequential
    single-engine oracle, the survivor leader's pool passing
    assert_consistent on clean stop."""
    procs, outs = _launch(_SERVE_WORKER, 5, "4", "tpgroup",
                          n_devices=1, timeout=420)
    codes = [p.returncode for p in procs]
    assert codes[2] == -9, \
        f"follower rank 2 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    assert "SERVE_TPGROUP_OK survivor=3" in outs[0], outs[0]
    # The doomed group's LEADER exits alive but reports the follower
    # death — any-shard death fails the whole group.
    assert codes[1] == 0, f"doomed group leader crashed:\n{outs[1]}"
    assert "SERVE_REPLICA_OK 1 follower gone" in outs[1], outs[1]
    # Survivor group: leader stops cleanly (assert_consistent inside),
    # its follower replays to the end and stops on the leader's signal.
    assert codes[3] == 0, f"survivor leader failed:\n{outs[3]}"
    assert "SERVE_REPLICA_OK 3 stopped" in outs[3], outs[3]
    assert codes[4] == 0, f"survivor follower failed:\n{outs[4]}"
    assert "SERVE_REPLICA_OK 4 stopped" in outs[4], outs[4]


def test_serving_traffic_soak_kill_at_peak_load():
    """The chaos-under-load soak: the fleet serves a seeded
    heavy-tailed workload (MMPP bursts, Zipf shared prefixes, mixed
    length buckets) with the router's tracer wired to an SLO config,
    and the highest rank SIGKILLs itself at peak generated load with
    live sequences in its pool.  Three properties must hold at once:

    * every stream finishes BIT-IDENTICAL to the sequential
      single-engine oracle (failover replays committed prefixes);
    * at least one stream actually crossed the kill (failovers > 0);
    * every ``slo/burn_rate/*`` gauge stays below 1.0 — the cluster
      degraded gracefully instead of burning its error budget.
    """
    import re

    procs, outs = _launch(_SERVE_WORKER, 3, "6", "traffic",
                          n_devices=1, timeout=420)
    codes = [p.returncode for p in procs]
    assert codes[2] == -9, f"rank 2 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    m = re.search(r"SERVE_TRAFFIC_OK burn_max=([0-9.]+)", outs[0])
    assert m, outs[0]
    assert float(m.group(1)) < 1.0
    assert codes[1] == 0, f"survivor replica failed:\n{outs[1]}"
    assert "SERVE_REPLICA_OK 1" in outs[1], outs[1]


def test_serving_fleet_metrics_scrape_survives_kill9(tmp_path):
    """The fleet observability soak: the kill9 topology (router + 2
    replicas, highest rank SIGKILLed mid-stream) with every request
    carrying a tenant id and the router serving its merged fleet view
    at a live ``/metrics`` endpoint that a rank-0 thread scrapes
    throughout.  On top of the bit-exact failover, the scrape series
    must show: the dead replica's per-replica series present while it
    lived and GONE from the final view (health.forget drops them within
    one beat), fleet counters monotone on either side of the single
    step-down where the dead snapshot left the merge, and per-tenant
    token counters that survived the failover re-billing."""
    import re

    procs, outs = _launch(_SERVE_WORKER, 3, "12", f"metrics:{tmp_path}",
                          n_devices=1, timeout=420)
    codes = [p.returncode for p in procs]
    assert codes[2] == -9, f"rank 2 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    m = re.search(r"SERVE_METRICS_OK scrapes=(\d+)", outs[0])
    assert m, outs[0]
    assert int(m.group(1)) >= 3
    assert codes[1] == 0, f"survivor replica failed:\n{outs[1]}"
    assert "SERVE_REPLICA_OK 1" in outs[1], outs[1]


def test_serving_cluster_gossip_prefix_routing_kill9():
    """The cluster-global prefix index soak: router + 3 replicas running
    model-based speculative decode with chunked prefill.  Wave 1 seeds
    one replica with a 3-page template prompt while rank 1 — the
    cold-start placement favorite that owns the template request —
    SIGKILLs itself mid-stream, so the template's pages are re-prefilled
    on a survivor the router never deliberately warmed.  Wave 2's
    template-prefixed prompts (gated on wave 1 via after_gids) must
    route to that exact survivor purely via the gossiped digest view,
    and every stream — both waves, through the kill — must be
    bit-identical to the sequential single-engine oracle."""
    import re

    procs, outs = _launch(_SERVE_WORKER, 4, "6", "gossip",
                          n_devices=1, timeout=540)
    codes = [p.returncode for p in procs]
    assert codes[1] == -9, f"rank 1 should die by SIGKILL: {codes}\n" \
        + "\n".join(outs)
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    m = re.search(r"SERVE_GOSSIP_OK holder=(\d+)", outs[0])
    assert m, outs[0]
    assert int(m.group(1)) in (2, 3), outs[0]
    for r in (2, 3):
        assert codes[r] == 0, f"survivor replica {r} failed:\n{outs[r]}"
        assert f"SERVE_REPLICA_OK {r}" in outs[r], outs[r]


def test_serving_cluster_longctx_streaming_registration_soak():
    """Streaming prefix registration over the wire: a long document
    chunk-prefills on one replica, each completed slice's pages
    registering in the prefix index immediately and gossiping on the
    next load beat.  A doc-prefixed follower is gated on that gossiped
    partial view (after_index_pages) so it arrives MID-PREFILL, and
    must route to the warm replica — which the router only knows about
    through the streamed registrations — with both streams bit-exact
    against the sequential single-engine oracle."""
    import re

    procs, outs = _launch(_SERVE_WORKER, 3, "0", "longctx",
                          n_devices=1, timeout=420)
    codes = [p.returncode for p in procs]
    assert codes[0] == 0, f"router failed:\n{outs[0]}"
    assert "SERVE_SOAK_OK" in outs[0], outs[0]
    m = re.search(r"SERVE_LONGCTX_OK holder=(\d+)", outs[0])
    assert m, outs[0]
    assert int(m.group(1)) in (1, 2), outs[0]
    for r in (1, 2):
        assert codes[r] == 0, f"replica {r} failed:\n{outs[r]}"
        assert f"SERVE_REPLICA_OK {r}" in outs[r], outs[r]


# ---------------------------------------------------------------------------
# Elastic supervisor soaks: the WHOLE fault-tolerance loop over real
# process boundaries — heartbeat-deadline detection, bounded teardown,
# respawn/rescale, plan-validated resharding, and resume from the latest
# consistent checkpoint generation.
# ---------------------------------------------------------------------------

_ELASTIC_WORKER = os.path.join(
    os.path.dirname(__file__), "_elastic_train_worker.py"
)


def _run_elastic(workdir, ckpt, nproc, *extra, step_log=None, timeout=300):
    """One supervised job: supervisor CLI + nproc ranks of the elastic
    training worker.  Returns (proc, combined stdout, final report)."""
    import json

    env = subprocess_env(n_devices=1)
    cmd = [
        sys.executable, "-m", "chainermn_tpu.tools.elastic",
        "--nproc", str(nproc), "--workdir", str(workdir),
        "--hb-timeout", "30", "--grace", "5",
    ]
    if step_log is not None:
        cmd += ["--step-log", str(step_log)]
    cmd += [*extra, "--", sys.executable, _ELASTIC_WORKER,
            "--ckpt", str(ckpt)]
    try:
        p = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        pytest.fail(f"supervisor timed out:\n{e.stdout}")
    reports = [
        ln for ln in p.stdout.splitlines()
        if ln.startswith("ELASTIC_REPORT ")
    ]
    assert reports, p.stdout
    return p, p.stdout, json.loads(reports[-1].split(" ", 1)[1])


def _losses(out):
    """step -> loss from rank-0 echo lines; replayed steps overwrite."""
    import re

    return {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(r"step (\d+) loss ([0-9.]+)", out)
    }


@pytest.fixture(scope="module")
def elastic_oracle(tmp_path_factory):
    """Uninterrupted 2-rank supervised run — digest + loss baseline for
    the chaos variants below."""
    base = tmp_path_factory.mktemp("elastic_oracle")
    p, out, report = _run_elastic(base / "work", base / "ckpt", 2)
    assert p.returncode == 0, out
    assert report["status"] == "ok", report
    assert report["incarnations"] == 1, report
    assert report["params_digest"], report
    return {"digest": report["params_digest"], "losses": _losses(out)}


def test_elastic_supervisor_kill9_soak(tmp_path, elastic_oracle):
    """SIGKILL one rank mid-run: the supervisor detects, tears down the
    survivor, respawns the world, and the resumed run's final params
    digest is BIT-IDENTICAL to the uninterrupted oracle."""
    log = tmp_path / "steps.jsonl"
    p, out, report = _run_elastic(
        tmp_path / "work", tmp_path / "ckpt", 2,
        "--chaos", "kill:rank=1:step=5", step_log=log,
    )
    assert p.returncode == 0, out
    assert report["status"] == "ok", report
    assert report["restarts"] >= 1, report
    assert report["resume_generation"] is not None, report
    assert "chaos: SIGKILL" in out
    assert report["params_digest"] == elastic_oracle["digest"], (
        report, elastic_oracle["digest"], out,
    )

    # elastic/* counters flow through the shared observability pipeline
    from chainermn_tpu.observability.step_log import read_records
    from chainermn_tpu.tools.obs import summarize, to_prometheus

    summary = summarize(read_records(str(log)))
    assert summary["counters"]["elastic/restarts"] >= 1, summary
    assert summary["counters"]["elastic/resume_generation"] >= 1, summary
    assert summary["counters"]["elastic/preemptions"] == 0, summary
    prom = to_prometheus(summary)
    assert 'counter_total{name="elastic/restarts"}' in prom, prom


def test_elastic_supervisor_rescale_2_to_1_soak(tmp_path, elastic_oracle):
    """Kill a rank with --rescale-on-failure: the world restarts at
    N-1=1, restored state is re-placed through the ShardingPlan registry
    (plan-validated on the NEW mesh), and the resumed loss curve stays
    on the 2-rank oracle curve (same math up to summation order)."""
    p, out, report = _run_elastic(
        tmp_path / "work", tmp_path / "ckpt", 2,
        "--rescale-on-failure", "--min-nproc", "1",
        "--chaos", "kill:rank=1:step=4",
    )
    assert p.returncode == 0, out
    assert report["status"] == "ok", report
    assert report["world"] == 1, report
    assert report["restarts"] >= 1, report
    assert "elastic_reshard plan=dp ok=True" in out, out
    losses, oracle = _losses(out), elastic_oracle["losses"]
    assert losses, out
    for g, loss in losses.items():
        assert abs(loss - oracle[g]) <= 2e-3 * max(1.0, abs(oracle[g])), (
            g, loss, oracle[g],
        )


def test_elastic_supervisor_preemption_soak(tmp_path, elastic_oracle):
    """SIGTERM = preemption: grace-window synchronous checkpoint on ALL
    ranks, distinct exit code (counted as a preemption, not a restart),
    resumed run bit-identical to the oracle."""
    p, out, report = _run_elastic(
        tmp_path / "work", tmp_path / "ckpt", 2,
        "--chaos", "term:rank=0:step=6",
    )
    assert p.returncode == 0, out
    assert report["status"] == "ok", report
    assert report["preemptions"] >= 1, report
    assert report["restarts"] == 0, report
    assert "preempted: checkpoint saved at iteration 6" in out, out
    assert report["params_digest"] == elastic_oracle["digest"], (
        report, elastic_oracle["digest"],
    )


# ---------------------------------------------------------------------------
# Resource fabric: diurnal soak, chips traded between the planes
# ---------------------------------------------------------------------------
_FABRIC_TRAFFIC = ("requests=60,rate=30,burst=3,diurnal=0.6,"
                   "diurnal_period_s=6,tenants=2,vocab=24")


def _run_fabric(workdir, *extra, timeout=540):
    """One fabric run: elastic 2-rank trainer + 2-replica fleet + the
    chip arbiter, all under ``tools.fabric``.  Returns (proc, stdout,
    parsed FABRIC_REPORT)."""
    import json

    env = subprocess_env(n_devices=1)
    cmd = [
        sys.executable, "-m", "chainermn_tpu.tools.fabric",
        "--nproc", "2", "--replicas", "2", "--train-steps", "160",
        "--hb-timeout", "30", "--deadline-s", "90",
        "--traffic", _FABRIC_TRAFFIC,
        "--workdir", str(workdir), *extra,
    ]
    try:
        p = subprocess.run(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        pytest.fail(f"fabric driver timed out:\n{e.stdout}")
    reports = [
        ln for ln in p.stdout.splitlines()
        if ln.startswith("FABRIC_REPORT ")
    ]
    assert reports, p.stdout
    return p, p.stdout, json.loads(reports[-1].split(" ", 1)[1])


@pytest.fixture(scope="module")
def fabric_oracle(tmp_path_factory):
    """The no-arbiter arm: same diurnal workload, training at a flat
    2 ranks, fleet pinned at 2 replicas — digest + stream baseline."""
    base = tmp_path_factory.mktemp("fabric_oracle")
    p, out, report = _run_fabric(base / "work", "--no-arbiter")
    assert p.returncode == 0, out
    assert report["train"]["status"] == "ok", report["train"]
    assert report["train"]["params_digest"], report["train"]
    assert report["dropped_streams"] == 0, report
    assert report["parity"]["mismatches"] == [], report["parity"]
    return report


def test_fabric_diurnal_round_trip_soak(tmp_path, fabric_oracle):
    """The tentpole soak: under the diurnal day-curve the arbiter must
    complete a full chip round trip — preempt trainer ranks at the peak
    (grace checkpoint → exit 75 → respawn at N−k, backfill replica from
    the freed chips), return them at the trough (drain → migrate →
    retire → regrow) — while FOUR invariants hold at once:

    * training's final params digest is BIT-IDENTICAL to the
      uninterrupted no-arbiter oracle (the int64 gradient wire makes
      the digest world-size-invariant, so this pins exact resume);
    * zero dropped streams, every checked stream oracle-exact;
    * the chip ledger conserves ``granted + free == total`` across
      every recorded event;
    * the rescale waves ride the lease path (lease_rescales, not the
      crash-restart or preemption budgets).
    """
    p, out, report = _run_fabric(tmp_path / "work")
    assert p.returncode == 0, out
    tr = report["transitions"]
    assert tr["preempt_for_serving"] >= 1, report
    assert tr["return_to_training"] >= 1, report
    train = report["train"]
    assert train["status"] == "ok", train
    assert train["lease_rescales"] >= 2, train
    assert train["restarts"] == 0, train
    assert train["params_digest"] == \
        fabric_oracle["train"]["params_digest"], (
            train, fabric_oracle["train"])
    assert report["dropped_streams"] == 0, report
    assert report["parity"]["checked"] > 0, report["parity"]
    assert report["parity"]["mismatches"] == [], report["parity"]
    assert report["ledger_conserved"], report["ledger"]
    led = report["ledger"]
    assert led["granted"] + led["free"] == led["total"], led
    for ev in led["events"]:
        assert ev["granted"] + ev["free"] == ev["total"], ev
    assert all(b < 1.0 for b in report["burn_rates"].values()), report


def test_fabric_chaos_kill_mid_arbitration_soak(tmp_path,
                                                fabric_oracle):
    """SIGKILL a trainer rank while a chip transfer is in flight: the
    supervisor's crash path resumes from the newest consistent
    checkpoint generation, the arbiter's ledger stays conserved, and
    the digest still lands bit-identical to the oracle."""
    p, out, report = _run_fabric(
        tmp_path / "work", "--kill-rank-on-transfer", "1",
    )
    assert p.returncode == 0, out
    assert report["chaos_kill_fired"], report
    train = report["train"]
    assert train["status"] == "ok", train
    assert train["params_digest"] == \
        fabric_oracle["train"]["params_digest"], (
            train, fabric_oracle["train"])
    assert report["dropped_streams"] == 0, report
    assert report["parity"]["mismatches"] == [], report["parity"]
    assert report["ledger_conserved"], report["ledger"]
