"""Two-process jax.distributed integration test — the TPU-native analogue
of the reference's ``mpiexec -n 2 pytest`` CI trick (SURVEY §4): REAL
process boundaries, the coordinator standing in for MPI's control plane.
Exercises the cross-process object plane (bcast/gather/allreduce_obj),
host-plane p2p (send_obj/recv_obj over the KV store, incl. multi-chunk
payloads), barrier, dataset scattering, parameter broadcast, the
communicator × wire-dtype matrix, and a cross-process ZeRO-3 step."""

import os
import socket
import subprocess
import sys

import pytest

from conftest import subprocess_env

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_object_plane(tmp_path):
    port = _free_port()
    nproc = 2
    env = subprocess_env(n_devices=1)
    # Shared dir for the multi-host checkpointer round-trip in the worker.
    env["CHAINERMN_TPU_TEST_CKPT_DIR"] = str(tmp_path)

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multiprocess workers timed out:\n" + "\n".join(outs))

    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_WORKER_OK {i}" in out, f"worker {i} output:\n{out}"
