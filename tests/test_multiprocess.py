"""Two-process jax.distributed integration test — the TPU-native analogue
of the reference's ``mpiexec -n 2 pytest`` CI trick (SURVEY §4): REAL
process boundaries, the coordinator standing in for MPI's control plane.
Exercises the cross-process object plane (bcast/gather/allreduce_obj),
host-plane p2p (send_obj/recv_obj over the KV store, incl. multi-chunk
payloads), barrier, dataset scattering, parameter broadcast, the
communicator × wire-dtype matrix, and a cross-process ZeRO-3 step."""

import os
import socket
import subprocess
import sys

import pytest

from conftest import subprocess_env

pytestmark = pytest.mark.slow

_WORKER = os.path.join(os.path.dirname(__file__), "_mp_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_object_plane(tmp_path):
    port = _free_port()
    nproc = 2
    env = subprocess_env(n_devices=1)
    # Shared dir for the multi-host checkpointer round-trip in the worker.
    env["CHAINERMN_TPU_TEST_CKPT_DIR"] = str(tmp_path)

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multiprocess workers timed out:\n" + "\n".join(outs))

    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"MP_WORKER_OK {i}" in out, f"worker {i} output:\n{out}"


_DIVERGE_WORKER = os.path.join(
    os.path.dirname(__file__), "_mp_diverge_worker.py"
)


@pytest.mark.parametrize("mode", ["site", "ordinal"])
def test_construction_order_divergence_fails_fast(mode):
    """A rank-conditional create_communicator (breaching the SPMD
    construction contract the host plane's key namespaces rely on) must
    fail FAST with a diagnostic, not hang or deliver mixed-up payloads."""
    port = _free_port()
    env = subprocess_env(n_devices=1)
    procs = [
        subprocess.Popen(
            [sys.executable, _DIVERGE_WORKER, str(i), "2", str(port), mode],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(
            "divergence was not detected (workers hung):\n" + "\n".join(outs)
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"DIVERGE_OK {i}" in out, f"worker {i} output:\n{out}"
