"""Elastic supervisor + chaos harness unit tests (fast tier).

The supervisor is pure process plumbing, so everything here runs with
stdlib dummy ranks (``_elastic_dummy_worker.py``) — no jax, no
communicator stack.  The jax.distributed soaks (real training, real
kills, digest parity) live in ``test_multiprocess.py``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from conftest import subprocess_env

from chainermn_tpu.elastic import (
    EXIT_PREEMPTED,
    ChaosEngine,
    ChaosSchedule,
    ElasticSupervisor,
    Fault,
    FileBeat,
    HeartbeatMonitor,
    SupervisorConfig,
    read_beat,
)

_DUMMY = os.path.join(os.path.dirname(__file__), "_elastic_dummy_worker.py")


# ---------------------------------------------------------------------------
# chaos schedules
# ---------------------------------------------------------------------------

def test_chaos_schedule_roundtrip():
    text = ("kill:rank=1:step=5;term:rank=0:step=8;"
            "hb_stall:rank=1:step=3:secs=30;ckpt_corrupt:rank=0:gen=4;"
            "ckpt_torn:rank=1:gen=6;ckpt_slow:secs=0.05;"
            "kill:rank=0:step=2:inc=1")
    s = ChaosSchedule.parse(text)
    assert len(s.faults) == 7
    assert ChaosSchedule.parse(s.format()).format() == s.format()
    assert s.faults[0] == Fault(kind="kill", rank=1, step=5)
    assert s.faults[-1].inc == 1


@pytest.mark.parametrize("bad", [
    "explode:rank=1:step=5",        # unknown kind
    "kill:rank=1:step=5:when=now",  # unknown key
    "kill:rank=1",                  # missing required step
    "hb_stall:step=3",              # missing required secs
    "kill:rank=1:step5",            # not key=value
])
def test_chaos_schedule_rejects(bad):
    with pytest.raises(ValueError):
        ChaosSchedule.parse(bad)


def test_chaos_fault_targeting():
    f = Fault(kind="kill", rank=1, step=5)
    assert f.targets(rank=1, incarnation=0)
    assert not f.targets(rank=0, incarnation=0)
    assert not f.targets(rank=1, incarnation=2)  # inc defaults to 0
    every_inc = Fault(kind="kill", rank=1, step=5, inc=-1)
    assert every_inc.targets(rank=1, incarnation=7)
    any_rank = Fault(kind="term", step=2)
    assert any_rank.targets(rank=0, incarnation=0)
    assert any_rank.targets(rank=3, incarnation=0)

    s = ChaosSchedule.parse("kill:rank=1:step=5;term:rank=0:step=8:inc=2")
    assert [f.kind for f in s.for_rank(1, 0)] == ["kill"]
    assert s.for_rank(0, 0) == ()
    assert [f.kind for f in s.for_rank(0, 2)] == ["term"]


class _FakeBeat:
    def __init__(self):
        self.suppressed = []

    def suppress(self, secs):
        self.suppressed.append(secs)


def test_chaos_engine_hb_stall_fires_once():
    hb = _FakeBeat()
    eng = ChaosEngine(
        ChaosSchedule.parse("hb_stall:rank=0:step=3:secs=9"),
        rank=0, incarnation=0, heartbeat=hb,
    )
    eng.on_step(2)
    assert hb.suppressed == []
    eng.on_step(3)
    assert hb.suppressed == [9.0]
    eng.on_step(4)  # fired-once: a step fault never re-fires
    assert hb.suppressed == [9.0]


def test_chaos_engine_term_sends_sigterm():
    got = []
    prev = signal.signal(signal.SIGTERM, lambda *a: got.append(a[0]))
    try:
        eng = ChaosEngine(
            ChaosSchedule.parse("term:rank=0:step=1"),
            rank=0, incarnation=0,
        )
        eng.on_step(0)
        assert got == []
        eng.on_step(1)
        # delivery is on the next bytecode boundary; give it one
        time.sleep(0.01)
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


class _FakeCkpt:
    """Just enough checkpointer surface for wrap_checkpointer."""

    def __init__(self, path):
        self._path = str(path)
        self.saves = []

        class _C:
            rank = 0
        self.comm = _C()

    def save(self, state, iteration, block=True):
        self.saves.append((iteration, block))
        with open(self._path, "wb") as f:
            f.write(b"HDRxxxxpayloadCRC4")

    def wait(self):
        pass

    def _snap(self, iteration, rank):
        return self._path


def test_chaos_engine_corrupts_committed_snapshot(tmp_path):
    snap = tmp_path / "snap"
    ck = _FakeCkpt(snap)
    eng = ChaosEngine(
        ChaosSchedule.parse("ckpt_corrupt:rank=0:gen=2"),
        rank=0, incarnation=0,
    )
    eng.wrap_checkpointer(ck)
    ck.save({}, 1, block=False)
    assert snap.read_bytes() == b"HDRxxxxpayloadCRC4"
    ck.save({}, 2, block=False)
    damaged = snap.read_bytes()
    assert len(damaged) == 18 and damaged != b"HDRxxxxpayloadCRC4"
    # the flipped byte sits just before the trailing u32 crc
    assert damaged[-5] == (b"HDRxxxxpayloadCRC4"[-5] ^ 0xFF)
    # the corrupting save was forced synchronous
    assert ck.saves == [(1, False), (2, True)]


def test_chaos_engine_torn_truncates(tmp_path):
    snap = tmp_path / "snap"
    ck = _FakeCkpt(snap)
    eng = ChaosEngine(
        ChaosSchedule.parse("ckpt_torn:rank=0:gen=1"),
        rank=0, incarnation=0,
    )
    eng.wrap_checkpointer(ck)
    ck.save({}, 1)
    assert snap.read_bytes() == b"HDRxxxxpayloadCRC4"[:-7]


def test_chaos_engine_incarnation_gating():
    eng = ChaosEngine(
        ChaosSchedule.parse("kill:rank=0:step=1"),
        rank=0, incarnation=1,  # fault belongs to incarnation 0
    )
    eng.on_step(1)  # must NOT SIGKILL us
    assert eng._armed == []


# ---------------------------------------------------------------------------
# heartbeat module (shared with serving)
# ---------------------------------------------------------------------------

def test_heartbeat_monitor_shared_with_serving():
    from chainermn_tpu.elastic.heartbeat import HeartbeatMonitor as a
    from chainermn_tpu.serving.cluster import HeartbeatMonitor as b
    from chainermn_tpu.serving.cluster.health import HeartbeatMonitor as c
    assert a is b is c


def test_heartbeat_monitor_deadline_and_revival():
    t = [0.0]
    m = HeartbeatMonitor([0, 1], miss_after_s=1.0, clock=lambda: t[0])
    assert m.check() == []
    t[0] = 0.9
    m.beat(1)
    t[0] = 1.5
    assert m.check() == [0]      # rank 0 missed its deadline
    assert m.check() == []       # newly-dead reported exactly once
    assert not m.alive(0) and m.alive(1)
    m.beat(0)                    # replacement incarnation revives
    assert m.alive(0)
    t[0] = 10.0
    assert sorted(m.check()) == [0, 1]


def test_filebeat_and_read_beat(tmp_path):
    path = tmp_path / "hb" / "rank0"
    assert read_beat(str(path)) is None
    fb = FileBeat(str(path))
    fb.beat(7)
    m1 = read_beat(str(path))
    assert m1 is not None
    assert path.read_text() == "7"
    fb.suppress(60)
    fb.beat(8)                   # suppressed: no write
    assert path.read_text() == "7"
    assert read_beat(str(path)) == m1


# ---------------------------------------------------------------------------
# supervisor (in-process, dummy ranks)
# ---------------------------------------------------------------------------

def _config(tmp_path, mode, nproc=1, **kw):
    cfg = dict(
        argv=[sys.executable, _DUMMY, mode],
        nproc=nproc,
        heartbeat_timeout_s=1.0,
        start_grace_s=10.0,
        poll_s=0.02,
        grace_s=2.0,
        backoff_s=0.05,
        workdir=str(tmp_path / "sup"),
        echo=False,
        barrier_timeout_s=30.0,
    )
    cfg.update(kw)
    return SupervisorConfig(**cfg)


def test_supervisor_clean_run(tmp_path):
    report = ElasticSupervisor(_config(tmp_path, "ok")).run()
    assert report["status"] == "ok"
    assert report["restarts"] == 0
    assert report["preemptions"] == 0
    assert report["incarnations"] == 1
    assert report["params_digest"] == "abad1dea"


def test_supervisor_restarts_after_crash(tmp_path):
    sup = ElasticSupervisor(_config(tmp_path, "crash_once"))
    report = sup.run()
    assert report["status"] == "ok"
    assert report["restarts"] == 1
    assert report["incarnations"] == 2
    # dummy's incarnation-1 output carries "resumed from iteration 10"
    assert report["resume_generation"] == 10
    kinds = [e["kind"] for e in sup.events]
    assert "failure" in kinds and "success" in kinds


def test_supervisor_exhausts_restart_budget(tmp_path):
    t0 = time.monotonic()
    sup = ElasticSupervisor(
        _config(tmp_path, "crash_always", max_restarts=1)
    )
    report = sup.run()
    assert report["status"] == "failed"
    assert report["restarts"] == 2  # budget 1 exceeded on the 2nd crash
    assert report["incarnations"] == 2
    assert any(
        e["kind"] == "give_up" and e["reason"] == "max_restarts"
        for e in sup.events
    )
    assert time.monotonic() - t0 < 30  # bounded: no deadline-less waits


def test_supervisor_teardown_is_bounded_and_sigkills(tmp_path):
    """Rank 1 crashes while rank 0 ignores SIGTERM and beats forever:
    the supervisor must SIGKILL rank 0 within its grace window, then
    respawn and finish."""
    t0 = time.monotonic()
    sup = ElasticSupervisor(_config(tmp_path, "teardown", nproc=2))
    report = sup.run()
    elapsed = time.monotonic() - t0
    assert report["status"] == "ok"
    assert report["restarts"] == 1
    td = [e for e in sup.events if e["kind"] == "teardown"]
    assert any(0 in e["sigkilled"] for e in td), td
    assert elapsed < 30, f"teardown not bounded: {elapsed:.1f}s"


def test_supervisor_heartbeat_deadline_detects_stall(tmp_path):
    """Rank 1 stays alive but stops beating: only the heartbeat
    deadline can catch it (exit-code polling never fires)."""
    sup = ElasticSupervisor(
        _config(tmp_path, "stall", nproc=2, heartbeat_timeout_s=0.5,
                start_grace_s=5.0)
    )
    report = sup.run()
    assert report["status"] == "ok"
    assert report["restarts"] == 1
    fails = [e for e in sup.events if e["kind"] == "failure"]
    assert any(1 in e["heartbeat_dead"] for e in fails), fails


def test_supervisor_rescales_to_survivors(tmp_path):
    sup = ElasticSupervisor(
        _config(tmp_path, "crash_rank1_once", nproc=2,
                rescale_on_failure=True, min_nproc=1)
    )
    report = sup.run()
    assert report["status"] == "ok"
    assert report["nproc"] == 2
    assert report["world"] == 1  # shrank to the survivor count
    assert any(
        e["kind"] == "rescale" and e["to_world"] == 1 for e in sup.events
    )


def test_supervisor_counts_preemption_separately(tmp_path):
    report = ElasticSupervisor(_config(tmp_path, "preempt_once")).run()
    assert report["status"] == "ok"
    assert report["preemptions"] == 1
    assert report["restarts"] == 0  # preemption is not a crash
    assert report["incarnations"] == 2
    assert report["exit_codes"] == {"0": 0}


def test_supervisor_counters_through_obs(tmp_path):
    """The elastic/* counters ride the step log into tools.obs
    summarize and the Prometheus exporter."""
    log = tmp_path / "sup.jsonl"
    ElasticSupervisor(
        _config(tmp_path, "crash_once", step_log=str(log))
    ).run()
    from chainermn_tpu.observability.step_log import read_records
    from chainermn_tpu.tools.obs import summarize, to_prometheus

    rows = read_records(str(log))
    summary = summarize(rows)
    assert summary["counters"]["elastic/restarts"] == 1
    assert summary["counters"]["elastic/preemptions"] == 0
    assert summary["counters"]["elastic/resume_generation"] == 10
    prom = to_prometheus(summary)
    assert 'counter_total{name="elastic/restarts"} 1' in prom
    # supervisor lifecycle rows are regular events in the same log
    kinds = {r.get("kind") for r in rows if r.get("event") == "elastic"}
    assert {"spawn", "failure", "teardown", "success"} <= kinds


# ---------------------------------------------------------------------------
# crash postmortem (global_except_hook satellite)
# ---------------------------------------------------------------------------

def test_postmortem_row_written_on_crash(tmp_path):
    pm = tmp_path / "postmortem.jsonl"
    code = (
        "import chainermn_tpu.global_except_hook as geh\n"
        "geh.add_hook()\n"
        "geh.set_current_step(7)\n"
        "raise RuntimeError('chaos-postmortem-test')\n"
    )
    env = subprocess_env(n_devices=1)
    env["CHAINERMN_TPU_POSTMORTEM_FILE"] = str(pm)
    env["CHAINERMN_TPU_ELASTIC_RANK"] = "3"
    res = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert res.returncode == 13, res.stderr  # the crash barrier's exit
    from chainermn_tpu.observability.step_log import read_records

    rows = [r for r in read_records(str(pm)) if r.get("event") == "crash"]
    assert len(rows) == 1
    row = rows[0]
    assert row["rank"] == 3
    assert row["step"] == 7
    assert "RuntimeError" in row["exc"]
    assert "chaos-postmortem-test" in row["traceback"]


def test_postmortem_file_tolerates_torn_tail(tmp_path):
    """O_APPEND rows survive a torn tail: read_records must still
    return the intact rows."""
    pm = tmp_path / "postmortem.jsonl"
    row = json.dumps({"event": "crash", "rank": 0, "step": 1,
                      "exc": "X", "traceback": "tb", "t": 0.0, "size": 1})
    pm.write_text(row + "\n" + row[: len(row) // 2])
    from chainermn_tpu.observability.step_log import read_records

    rows = read_records(str(pm))
    assert len(rows) == 1 and rows[0]["rank"] == 0


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_smoke(tmp_path):
    env = subprocess_env(n_devices=1)
    res = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.elastic",
         "--nproc", "1", "--max-restarts", "0", "--no-echo",
         "--workdir", str(tmp_path / "sup"),
         "--", sys.executable, "-c", "print('hello from the rank')"],
        capture_output=True, text=True, env=env, timeout=180,
        cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout + res.stderr
    line = [ln for ln in res.stdout.splitlines()
            if ln.startswith("ELASTIC_REPORT ")]
    assert len(line) == 1
    report = json.loads(line[0].split(" ", 1)[1])
    assert report["status"] == "ok"
    assert report["nproc"] == 1


def test_cli_rejects_bad_chaos_schedule(tmp_path):
    env = subprocess_env(n_devices=1)
    res = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.elastic",
         "--nproc", "1", "--chaos", "explode:rank=0:step=1",
         "--workdir", str(tmp_path / "sup"),
         "--", sys.executable, "-c", "print('never runs')"],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=str(tmp_path),
    )
    assert res.returncode != 0
    assert "never runs" not in res.stdout


def test_cli_requires_command(tmp_path):
    env = subprocess_env(n_devices=1)
    res = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.elastic",
         "--nproc", "1"],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=str(tmp_path),
    )
    assert res.returncode == 2  # argparse usage error
