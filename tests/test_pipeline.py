"""spmd_pipeline tests: the stacked-stage GPipe schedule must match running
the stages sequentially on one device, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import build_mesh
from chainermn_tpu.parallel.pipeline import (
    pipeline_forward_and_loss,
    spmd_pipeline,
)

# Version-compat wrapper: forwards check_vma under whichever
# replication-check kwarg spelling this jax accepts.
from chainermn_tpu.communicators.base import shard_map_compat as shard_map


N_STAGES = 4
D = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stacked_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_STAGES)
    return {
        "w": jnp.stack([jax.random.normal(k, (D, D)) * 0.5 for k in ks]),
        "b": jnp.stack([jnp.zeros((D,)) for _ in ks]),
    }


def sequential_oracle(stacked, x):
    for i in range(N_STAGES):
        x = stage_fn(jax.tree.map(lambda p: p[i], stacked), x)
    return x


@pytest.fixture(scope="module")
def pp_mesh():
    devs = jax.devices()
    if len(devs) < N_STAGES:
        pytest.skip("needs 4 devices")
    return build_mesh(inter_size=1, intra_size=N_STAGES, devices=devs[:N_STAGES])


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_pipeline_matches_sequential(pp_mesh, n_micro):
    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))

    def body(stacked, x):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        out = spmd_pipeline(stage_fn, mine, x, "intra", n_micro)
        # Output lives on the last stage; broadcast for comparison.
        return jax.lax.psum(out, "intra")

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P()), out_specs=P(),
            check_vma=False,
        )
    )
    out = f(stacked, x)
    ref = sequential_oracle(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match(pp_mesh):
    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))

    def loss_on_out(out, target):
        return jnp.mean((out - target) ** 2)

    def dist_loss(stacked):
        def body(stacked, x, tgt):
            mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
            return pipeline_forward_and_loss(
                stage_fn, loss_on_out, mine, x, tgt, "intra", 2
            )

        f = shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P()), out_specs=P(),
            check_vma=False,
        )
        return f(stacked, x, tgt)

    def ref_loss(stacked):
        return loss_on_out(sequential_oracle(stacked, x), tgt)

    g_dist = jax.jit(jax.grad(dist_loss))(stacked)
    g_ref = jax.grad(ref_loss)(stacked)
    for gd, gr in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_bad_microbatch(pp_mesh):
    stacked = make_stacked_params()
    x = jnp.ones((6, D))

    def body(stacked, x):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        return spmd_pipeline(stage_fn, mine, x, "intra", 4)

    f = shard_map(
        body, mesh=pp_mesh, in_specs=(P("intra"), P()), out_specs=P(),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(stacked, x)


@pytest.mark.parametrize("n_micro", [2, 4, 8])
def test_pipeline_1f1b_matches_oracle(pp_mesh, n_micro):
    """The interleaved 1F1B schedule's explicit-vjp (loss, grads) must match
    the sequential oracle's jax.grad exactly."""
    from chainermn_tpu.parallel.pipeline import pipeline_1f1b_loss_and_grads

    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))

    def loss_on_out(out, target):
        return jnp.mean((out - target) ** 2)

    def body(stacked, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        loss, g = pipeline_1f1b_loss_and_grads(
            stage_fn, loss_on_out, mine, x, tgt, "intra", n_micro
        )
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), g)

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P()),
            out_specs=(P(), P("intra")),
            check_vma=False,
        )
    )
    loss, grads = f(stacked, x, tgt)

    def ref_loss(stacked):
        return loss_on_out(sequential_oracle(stacked, x), tgt)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(stacked)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    for gd, gr in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_g)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


def test_pipeline_1f1b_with_head_and_input_grads(pp_mesh):
    """Composed form: head params inside the schedule, input cotangents out
    — embed/head gradients must match end-to-end jax.grad."""
    from chainermn_tpu.parallel.pipeline import pipeline_1f1b_loss_and_grads

    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))
    embed_w = jax.random.normal(jax.random.PRNGKey(3), (D, D)) * 0.5
    head_w = jax.random.normal(jax.random.PRNGKey(4), (D, D)) * 0.5

    def head_loss(hw, out, target):
        return jnp.mean((out @ hw - target) ** 2)

    def body(stacked, embed_w, head_w, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        tokens, embed_vjp = jax.vjp(lambda w: jnp.tanh(x @ w), embed_w)
        loss, sg, hg, gtok = pipeline_1f1b_loss_and_grads(
            stage_fn, head_loss, mine, tokens, tgt, "intra", 4,
            loss_params=head_w, with_input_grads=True,
        )
        gtok = jax.lax.psum(gtok, "intra")     # stage-0 owner
        hg = jax.lax.psum(hg, "intra")         # last-stage owner
        (eg,) = embed_vjp(gtok)
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), sg), eg, hg

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P(), P(), P()),
            out_specs=(P(), P("intra"), P(), P()),
            check_vma=False,
        )
    )
    loss, sg, eg, hg = f(stacked, embed_w, head_w, x, tgt)

    def ref_loss(stacked, embed_w, head_w):
        out = sequential_oracle(stacked, jnp.tanh(x @ embed_w))
        return head_loss(head_w, out, tgt)

    ref_l, (ref_sg, ref_eg, ref_hg) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2)
    )(stacked, embed_w, head_w)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(eg), np.asarray(ref_eg), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg), np.asarray(ref_hg), rtol=1e-4, atol=1e-5)
    for gd, gr in zip(jax.tree.leaves(sg), jax.tree.leaves(ref_sg)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) 1F1B
# ---------------------------------------------------------------------------


def make_chunked_params(n_chunks, seed=0):
    """(n_stages, n_chunks, ...) stacked params: device d's chunk l is
    GLOBAL stage l*n + d (the interleaved assignment)."""
    L = N_STAGES * n_chunks
    ks = jax.random.split(jax.random.PRNGKey(seed), L)
    full = {
        "w": jnp.stack([jax.random.normal(k, (D, D)) * 0.5 for k in ks]),
        "b": jnp.stack([jnp.zeros((D,)) for _ in ks]),
    }
    # stage s = l*n + d  →  [d][l] = full[s]
    per_dev = jax.tree.map(
        lambda p: jnp.stack([
            jnp.stack([p[l * N_STAGES + d] for l in range(n_chunks)])
            for d in range(N_STAGES)
        ]),
        full,
    )
    return full, per_dev


def sequential_oracle_L(full, x, L):
    for s in range(L):
        x = stage_fn(jax.tree.map(lambda p: p[s], full), x)
    return x


@pytest.mark.parametrize("n_chunks,n_micro", [(2, 4), (2, 8), (3, 4)])
def test_interleaved_1f1b_matches_oracle(pp_mesh, n_chunks, n_micro):
    """v chunks per device: loss and per-chunk grads must match jax.grad
    of the L = n*v stage sequential oracle exactly."""
    from chainermn_tpu.parallel.pipeline import (
        pipeline_interleaved_1f1b_loss_and_grads,
    )

    L = N_STAGES * n_chunks
    full, per_dev = make_chunked_params(n_chunks)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))

    def loss_on_out(out, target):
        return jnp.mean((out - target) ** 2)

    def body(per_dev, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        loss, g = pipeline_interleaved_1f1b_loss_and_grads(
            stage_fn, loss_on_out, mine, x, tgt, "intra", n_micro,
            n_chunks,
        )
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), g)

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P()),
            out_specs=(P(), P("intra")),
            check_vma=False,
        )
    )
    loss, grads = f(per_dev, x, tgt)

    def ref_loss(full):
        return loss_on_out(sequential_oracle_L(full, x, L), tgt)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(full)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    # Re-interleave the oracle grads into the (n, v, ...) layout.
    ref_per_dev = jax.tree.map(
        lambda p: jnp.stack([
            jnp.stack([p[l * N_STAGES + d] for l in range(n_chunks)])
            for d in range(N_STAGES)
        ]),
        ref_g,
    )
    for gd, gr in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_per_dev)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


def test_interleaved_1f1b_head_and_input_grads(pp_mesh):
    """Composed form with v=2: head inside the schedule, input cotangents
    out; all grads match end-to-end jax.grad."""
    from chainermn_tpu.parallel.pipeline import (
        pipeline_interleaved_1f1b_loss_and_grads,
    )

    n_chunks = 2
    L = N_STAGES * n_chunks
    full, per_dev = make_chunked_params(n_chunks)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))
    embed_w = jax.random.normal(jax.random.PRNGKey(3), (D, D)) * 0.5
    head_w = jax.random.normal(jax.random.PRNGKey(4), (D, D)) * 0.5

    def head_loss(hw, out, target):
        return jnp.mean((out @ hw - target) ** 2)

    def body(per_dev, embed_w, head_w, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        tokens, embed_vjp = jax.vjp(lambda w: jnp.tanh(x @ w), embed_w)
        loss, sg, hg, gtok = pipeline_interleaved_1f1b_loss_and_grads(
            stage_fn, head_loss, mine, tokens, tgt, "intra", 4, n_chunks,
            loss_params=head_w, with_input_grads=True,
        )
        gtok = jax.lax.psum(gtok, "intra")
        hg = jax.lax.psum(hg, "intra")
        (eg,) = embed_vjp(gtok)
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), sg), eg, hg

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P(), P(), P()),
            out_specs=(P(), P("intra"), P(), P()),
            check_vma=False,
        )
    )
    loss, sg, eg, hg = f(per_dev, embed_w, head_w, x, tgt)

    def ref_loss(full, embed_w, head_w):
        out = sequential_oracle_L(full, jnp.tanh(x @ embed_w), L)
        return head_loss(head_w, out, tgt)

    ref_l, (ref_sg, ref_eg, ref_hg) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2)
    )(full, embed_w, head_w)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(eg), np.asarray(ref_eg), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg), np.asarray(ref_hg), rtol=1e-4, atol=1e-5)
    ref_per_dev = jax.tree.map(
        lambda p: jnp.stack([
            jnp.stack([p[l * N_STAGES + d] for l in range(n_chunks)])
            for d in range(N_STAGES)
        ]),
        ref_sg,
    )
    for gd, gr in zip(jax.tree.leaves(sg), jax.tree.leaves(ref_per_dev)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


def test_interleaved_rejects_bad_round(pp_mesh):
    from chainermn_tpu.parallel.pipeline import (
        pipeline_interleaved_1f1b_loss_and_grads,
    )

    _full, per_dev = make_chunked_params(2)
    x = jnp.ones((6, D))
    tgt = jnp.ones((6, D))

    def body(per_dev, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        loss, _ = pipeline_interleaved_1f1b_loss_and_grads(
            stage_fn, lambda o, t: jnp.mean((o - t) ** 2), mine, x, tgt,
            "intra", 6, 2,
        )
        return loss

    f = shard_map(
        body, mesh=pp_mesh, in_specs=(P("intra"), P(), P()), out_specs=P(),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="rounds"):
        jax.jit(f)(per_dev, x, tgt)


# ---------------------------------------------------------------------------
# Circular (Megatron-tight) interleaved schedule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_chunks,n_micro", [(1, 4), (2, 4), (2, 8), (3, 4)])
def test_circular_1f1b_matches_oracle(pp_mesh, n_chunks, n_micro):
    """Buffered-admission circular schedule: loss and per-chunk grads must
    match jax.grad of the L = n*v stage sequential oracle — the trajectory
    equality that lets it replace the coupled interleaved scheduler."""
    from chainermn_tpu.parallel.pipeline import (
        pipeline_circular_1f1b_loss_and_grads,
    )

    L = N_STAGES * n_chunks
    full, per_dev = make_chunked_params(n_chunks)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))

    def loss_on_out(out, target):
        return jnp.mean((out - target) ** 2)

    def body(per_dev, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        loss, g = pipeline_circular_1f1b_loss_and_grads(
            stage_fn, loss_on_out, mine, x, tgt, "intra", n_micro, n_chunks,
        )
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), g)

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P()),
            out_specs=(P(), P("intra")),
            check_vma=False,
        )
    )
    loss, grads = f(per_dev, x, tgt)

    def ref_loss(full):
        return loss_on_out(sequential_oracle_L(full, x, L), tgt)

    ref_l, ref_g = jax.value_and_grad(ref_loss)(full)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    ref_per_dev = jax.tree.map(
        lambda p: jnp.stack([
            jnp.stack([p[l * N_STAGES + d] for l in range(n_chunks)])
            for d in range(N_STAGES)
        ]),
        ref_g,
    )
    for gd, gr in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_per_dev)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


def test_circular_1f1b_head_and_input_grads(pp_mesh):
    """Composed form: head inside, input cotangents out — all grads match
    end-to-end jax.grad (same contract as the coupled scheduler)."""
    from chainermn_tpu.parallel.pipeline import (
        pipeline_circular_1f1b_loss_and_grads,
    )

    n_chunks = 2
    L = N_STAGES * n_chunks
    full, per_dev = make_chunked_params(n_chunks)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))
    embed_w = jax.random.normal(jax.random.PRNGKey(3), (D, D)) * 0.5
    head_w = jax.random.normal(jax.random.PRNGKey(4), (D, D)) * 0.5

    def head_loss(hw, out, target):
        return jnp.mean((out @ hw - target) ** 2)

    def body(per_dev, embed_w, head_w, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        tokens, embed_vjp = jax.vjp(lambda w: jnp.tanh(x @ w), embed_w)
        loss, sg, hg, gtok = pipeline_circular_1f1b_loss_and_grads(
            stage_fn, head_loss, mine, tokens, tgt, "intra", 4, n_chunks,
            loss_params=head_w, with_input_grads=True,
        )
        gtok = jax.lax.psum(gtok, "intra")
        hg = jax.lax.psum(hg, "intra")
        (eg,) = embed_vjp(gtok)
        return loss, jax.tree.map(lambda a: jnp.expand_dims(a, 0), sg), eg, hg

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P(), P(), P()),
            out_specs=(P(), P("intra"), P(), P()),
            check_vma=False,
        )
    )
    loss, sg, eg, hg = f(per_dev, embed_w, head_w, x, tgt)

    def ref_loss(full, embed_w, head_w):
        out = sequential_oracle_L(full, jnp.tanh(x @ embed_w), L)
        return head_loss(head_w, out, tgt)

    ref_l, (ref_sg, ref_eg, ref_hg) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2)
    )(full, embed_w, head_w)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(eg), np.asarray(ref_eg), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hg), np.asarray(ref_hg), rtol=1e-4, atol=1e-5)
    ref_per_dev = jax.tree.map(
        lambda p: jnp.stack([
            jnp.stack([p[l * N_STAGES + d] for l in range(n_chunks)])
            for d in range(N_STAGES)
        ]),
        ref_sg,
    )
    for gd, gr in zip(jax.tree.leaves(sg), jax.tree.leaves(ref_per_dev)):
        np.testing.assert_allclose(
            np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5
        )


def test_circular_schedule_accounting():
    """The Megatron-bound claim, proven on the schedule algebra itself:
    for assorted (n, M, v), every device's work stream is gapless, every
    handoff arrives exactly one tick before consumption (shift-register
    depth 1 — buffered admission makes deeper queues unnecessary), and
    the total is M*v + n - 1 ticks: bubble (n-1) forward, hence
    2(n-1)/(2Mv) = (n-1)/(v*M) relative for the AD-mirrored step."""
    from chainermn_tpu.parallel.pipeline import circular_schedule_ticks

    for n, M, v in [(2, 4, 2), (4, 4, 2), (4, 8, 3), (3, 6, 4), (4, 4, 1)]:
        # t(m, s): unit (microbatch m, global stage s = l*n + d) runs on
        # device d at tick d + r*n*v + l*n + j, with m = r*n + j.
        def t_of(m, s):
            d, l = s % n, s // n
            r, j = divmod(m, n)
            return d + r * n * v + l * n + j

        L = n * v
        ticks_per_dev = {d: [] for d in range(n)}
        for m in range(M):
            for s in range(L):
                t = t_of(m, s)
                ticks_per_dev[s % n].append(t)
                if s > 0:
                    # Producer ran strictly one tick earlier: the single
                    # ppermute shift register delivers just in time.
                    assert t_of(m, s - 1) == t - 1, (n, M, v, m, s)
        for d, ts in ticks_per_dev.items():
            ts = sorted(ts)
            assert ts == list(range(d, d + M * v)), (n, M, v, d)
        T = max(max(ts) for ts in ticks_per_dev.values()) + 1
        assert T == circular_schedule_ticks(n, M, v) == M * v + n - 1


def test_circular_scan_length_is_tight(pp_mesh):
    """Structural check on the compiled program: the circular pipeline's
    scan runs exactly M*v + n - 1 ticks (the coupled scheduler's scan
    would be M*v + n*v + n - 2)."""
    from chainermn_tpu.parallel.pipeline import (
        circular_schedule_ticks,
        spmd_pipeline_circular,
    )

    n_chunks, n_micro = 2, 8
    _full, per_dev = make_chunked_params(n_chunks)
    x = jnp.ones((8, D))

    def body(per_dev, x):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        return spmd_pipeline_circular(
            stage_fn, mine, x, "intra", n_micro, n_chunks
        )

    f = shard_map(
        body, mesh=pp_mesh, in_specs=(P("intra"), P()), out_specs=P("intra"),
        check_vma=False,
    )
    jaxpr = jax.make_jaxpr(f)(per_dev, x)
    want = circular_schedule_ticks(N_STAGES, n_micro, n_chunks)

    def scan_lengths(jx):
        out = []
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                out.append(eqn.params["length"])
            for p in eqn.params.values():
                # Params hold sub-programs as Jaxpr (has .eqns) or
                # ClosedJaxpr (.jaxpr.eqns) depending on the primitive.
                sub = p.jaxpr if hasattr(p, "jaxpr") else p
                if hasattr(sub, "eqns"):
                    out.extend(scan_lengths(sub))
        return out

    lengths = scan_lengths(jaxpr.jaxpr)
    assert want in lengths, (lengths, want)


def test_circular_rejects_bad_round(pp_mesh):
    from chainermn_tpu.parallel.pipeline import (
        pipeline_circular_1f1b_loss_and_grads,
    )

    _full, per_dev = make_chunked_params(2)
    x = jnp.ones((6, D))
    tgt = jnp.ones((6, D))

    def body(per_dev, x, tgt):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), per_dev)
        loss, _ = pipeline_circular_1f1b_loss_and_grads(
            stage_fn, lambda o, t: jnp.mean((o - t) ** 2), mine, x, tgt,
            "intra", 6, 2,
        )
        return loss

    f = shard_map(
        body, mesh=pp_mesh, in_specs=(P("intra"), P(), P()), out_specs=P(),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="rounds"):
        jax.jit(f)(per_dev, x, tgt)


# ---------------------------------------------------------------------------
# Serving decode microbatches: the tp x pp composition's host-side split
# ---------------------------------------------------------------------------


def test_decode_microbatches_contiguous_even_with_leading_remainder():
    from chainermn_tpu.parallel.pipeline import decode_microbatches

    assert decode_microbatches(4, 2) == [(0, 2), (2, 4)]
    assert decode_microbatches(5, 2) == [(0, 3), (3, 5)]   # rem leads
    assert decode_microbatches(7, 3) == [(0, 3), (3, 5), (5, 7)]
    # fewer rows than stages: one row per span, never an empty span
    assert decode_microbatches(2, 4) == [(0, 1), (1, 2)]
    assert decode_microbatches(1, 4) == [(0, 1)]
    assert decode_microbatches(0, 4) == []
    # degenerate pipeline: the whole batch is one step
    assert decode_microbatches(6, 1) == [(0, 6)]
    # exhaustive contiguity/coverage sweep
    for n in range(1, 9):
        for s in range(1, 5):
            spans = decode_microbatches(n, s)
            assert spans[0][0] == 0 and spans[-1][1] == n
            assert all(a2 == b1 for (_, b1), (a2, _) in
                       zip(spans, spans[1:]))
            sizes = [b - a for a, b in spans]
            assert max(sizes) - min(sizes) <= 1
            assert all(sz > 0 for sz in sizes)


def test_serve_pipeline_order_is_gpipe_wavefront():
    from chainermn_tpu.parallel.pipeline import serve_pipeline_order

    order = serve_pipeline_order(3, 2)
    # microbatch m enters stage s at tick m + s
    assert order == [(0, 0, 0), (1, 0, 1), (1, 1, 0), (2, 0, 2),
                     (2, 1, 1), (3, 1, 2)]
    for n_micro, n_stages in ((1, 1), (4, 2), (2, 3)):
        o = serve_pipeline_order(n_micro, n_stages)
        # every (stage, micro) pair exactly once
        assert len(o) == n_micro * n_stages
        assert len({(s, m) for _, s, m in o}) == n_micro * n_stages
        assert all(t == s + m for t, s, m in o)
        # fill-drain latency: last tick is the GPipe bound
        assert o[-1][0] == n_micro + n_stages - 2
