"""spmd_pipeline tests: the stacked-stage GPipe schedule must match running
the stages sequentially on one device, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import build_mesh
from chainermn_tpu.parallel.pipeline import (
    pipeline_forward_and_loss,
    spmd_pipeline,
)

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


N_STAGES = 4
D = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stacked_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_STAGES)
    return {
        "w": jnp.stack([jax.random.normal(k, (D, D)) * 0.5 for k in ks]),
        "b": jnp.stack([jnp.zeros((D,)) for _ in ks]),
    }


def sequential_oracle(stacked, x):
    for i in range(N_STAGES):
        x = stage_fn(jax.tree.map(lambda p: p[i], stacked), x)
    return x


@pytest.fixture(scope="module")
def pp_mesh():
    devs = jax.devices()
    if len(devs) < N_STAGES:
        pytest.skip("needs 4 devices")
    return build_mesh(inter_size=1, intra_size=N_STAGES, devices=devs[:N_STAGES])


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_pipeline_matches_sequential(pp_mesh, n_micro):
    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))

    def body(stacked, x):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        out = spmd_pipeline(stage_fn, mine, x, "intra", n_micro)
        # Output lives on the last stage; broadcast for comparison.
        return jax.lax.psum(out, "intra")

    f = jax.jit(
        shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P()), out_specs=P(),
            check_vma=False,
        )
    )
    out = f(stacked, x)
    ref = sequential_oracle(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match(pp_mesh):
    stacked = make_stacked_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (8, D))

    def loss_on_out(out, target):
        return jnp.mean((out - target) ** 2)

    def dist_loss(stacked):
        def body(stacked, x, tgt):
            mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
            return pipeline_forward_and_loss(
                stage_fn, loss_on_out, mine, x, tgt, "intra", 2
            )

        f = shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("intra"), P(), P()), out_specs=P(),
            check_vma=False,
        )
        return f(stacked, x, tgt)

    def ref_loss(stacked):
        return loss_on_out(sequential_oracle(stacked, x), tgt)

    g_dist = jax.jit(jax.grad(dist_loss))(stacked)
    g_ref = jax.grad(ref_loss)(stacked)
    for gd, gr in zip(jax.tree.leaves(g_dist), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(gd), np.asarray(gr), rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_bad_microbatch(pp_mesh):
    stacked = make_stacked_params()
    x = jnp.ones((6, D))

    def body(stacked, x):
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), stacked)
        return spmd_pipeline(stage_fn, mine, x, "intra", 4)

    f = shard_map(
        body, mesh=pp_mesh, in_specs=(P("intra"), P()), out_specs=P(),
        check_vma=False,
    )
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(stacked, x)
