"""Communicator tests, shaped like the reference's
tests/communicator_tests/test_communicator.py (SURVEY §4): parameterized
over every communicator class, round-tripping broadcast_data /
allreduce_grad on a toy parameter tree and asserting against the
single-process (numpy) oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators import (
    build_mesh,
    create_communicator,
)

ALL_NAMES = [
    "naive",
    "flat",
    "xla_ici",
    "pure_nccl",
    "hierarchical",
    "two_dimensional",
]


def toy_tree(rank, dtype=jnp.float32):
    """A toy 'model' gradient tree whose values differ per rank."""
    r = float(rank)
    return {
        "w": jnp.arange(12.0, dtype=dtype).reshape(3, 4) + r,
        "b": jnp.full((5,), r, dtype),
        "scalar": jnp.asarray(2.0 * r + 1.0, dtype),
    }


def stacked_tree(n, dtype=jnp.float32):
    trees = [toy_tree(r, dtype) for r in range(n)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


@pytest.mark.parametrize("name", ALL_NAMES)
def test_allreduce_grad_matches_oracle(mesh, name):
    comm = create_communicator(name, mesh=mesh)
    n = comm.device_size
    stacked = stacked_tree(n)

    out = comm.eager_allreduce_grad(stacked)

    expected = jax.tree.map(lambda x: np.mean(np.asarray(x), axis=0), stacked)
    for k in ("w", "b", "scalar"):
        got = np.asarray(out[k])
        for r in range(n):
            np.testing.assert_allclose(got[r], expected[k], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["xla_ici", "hierarchical", "two_dimensional"])
def test_allreduce_grad_dtype_cast(mesh, name):
    """bf16 comm dtype: result dtype preserved, values ~mean (analogue of
    pure_nccl's fp16 allreduce_grad_dtype)."""
    comm = create_communicator(name, mesh=mesh, allreduce_grad_dtype=jnp.bfloat16)
    n = comm.device_size
    stacked = stacked_tree(n)
    out = comm.eager_allreduce_grad(stacked)
    assert out["w"].dtype == jnp.float32
    expected = np.mean(np.asarray(stacked["w"]), axis=0)
    np.testing.assert_allclose(np.asarray(out["w"])[0], expected, rtol=2e-2)


@pytest.mark.parametrize("name", ALL_NAMES)
def test_broadcast_data(mesh, name):
    comm = create_communicator(name, mesh=mesh)
    n = comm.device_size
    stacked = stacked_tree(n)
    out = comm.eager_broadcast_data(stacked, root=0)
    root_tree = toy_tree(0)
    for k in root_tree:
        got = np.asarray(out[k])
        for r in range(n):
            np.testing.assert_allclose(got[r], np.asarray(root_tree[k]))


def test_topology_properties(mesh):
    comm = create_communicator("xla_ici", mesh=mesh)
    assert comm.device_size == 8
    assert comm.inter_size * comm.intra_size == 8
    assert comm.rank == 0 and comm.size == 1  # single-process harness
    assert comm.intra_rank == 0
    assert len(comm.local_devices) == 8


def test_generic_collectives(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    x = jnp.arange(float(n))

    def body(xs):
        x = xs[0]  # scalar shard for this device
        s = comm.allreduce(x, "sum")
        m = comm.allreduce(x, "max")
        b = comm.bcast(x, root=3)
        g = comm.allgather(x[None])
        return s[None], m[None], b[None], g[None]

    f = jax.jit(
        comm.shard_map(
            body,
            in_specs=(comm._world_spec,),
            out_specs=(comm._world_spec,) * 4,
        )
    )
    s, m, b, g = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full(n, x.sum()))
    np.testing.assert_allclose(np.asarray(m), np.full(n, n - 1))
    np.testing.assert_allclose(np.asarray(b), np.full(n, 3.0))
    assert g.shape == (n, n, 1)
    for r in range(n):
        np.testing.assert_allclose(np.asarray(g[r]).ravel(), np.arange(n))


def test_scatter(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size

    # scatter: root 0 holds an (n*2,) array; every device gets its 2-chunk.
    data = jnp.arange(float(n * 2))

    def body(xs):
        chunk = comm.scatter(jnp.where(comm.axis_index() == 0, xs, 0.0), root=0)
        return chunk[None]

    f = jax.jit(
        comm.shard_map(
            body,
            in_specs=(P(),),
            out_specs=comm._world_spec,
        )
    )
    out = np.asarray(f(data))
    for r in range(n):
        np.testing.assert_allclose(out[r].ravel(), [2 * r, 2 * r + 1])


def test_gather_point_to_root(mesh):
    """gather is point-to-root (reference MPI_Gather): root receives the
    stack, everyone else zeros — and the lowering moves O(message) per
    source, not a world all_gather."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    root = n - 1

    def body(xs):
        return comm.gather(xs[0], root=root)[None]

    f = comm.shard_map(
        body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
    )
    out = np.asarray(jax.jit(f)(jnp.arange(1.0, n + 1.0)))
    np.testing.assert_allclose(out[root], np.arange(1.0, n + 1.0))
    for r in range(n):
        if r != root:
            np.testing.assert_allclose(out[r], np.zeros(n))
    assert "all_gather" not in str(
        jax.make_jaxpr(f)(jnp.arange(1.0, n + 1.0))
    )


def test_gather_grad_scatters_back(mesh):
    """Differentiating through point-to-root gather: each source receives
    exactly its slot's cotangent (the transpose of the per-source
    ppermutes)."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    root = 0
    weights = jnp.arange(1.0, n + 1.0)

    from jax import lax

    def loss(data):
        def body(xs):
            g = comm.gather(xs[0], root=root)
            # Only root's copy is meaningful; weight its entries.
            contrib = jnp.where(
                comm.axis_index() == root, jnp.sum(g * weights), 0.0
            )
            return lax.psum(contrib, comm.axes)[None]

        y = comm.shard_map(
            body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
        )(data)
        return y[0]

    g = np.asarray(jax.jit(jax.grad(loss))(jnp.zeros(n)))
    # Source r's value lands in slot r at root, so its cotangent is
    # weights[r].
    np.testing.assert_allclose(g, np.asarray(weights))


def test_scatter_avoids_world_broadcast(mesh):
    """The scatter lowering ships each destination only its own chunk — no
    bcast/psum of the whole buffer."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    data = jnp.arange(float(n * 2))

    def body(xs):
        return comm.scatter(xs, root=0)[None]

    jx = str(jax.make_jaxpr(
        comm.shard_map(body, in_specs=(P(),), out_specs=comm._world_spec)
    )(data))
    assert "all_gather" not in jx
    # The old lowering broadcast the whole buffer via masked psum.
    assert "psum" not in jx


def test_scatter_rejects_indivisible(mesh):
    comm = create_communicator("naive", mesh=mesh)

    def body(xs):
        return comm.scatter(xs, root=0)[None]

    f = comm.shard_map(body, in_specs=(P(),), out_specs=comm._world_spec)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(f)(jnp.arange(float(comm.device_size * 2 + 1)))


def test_alltoall(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    # Each rank r holds row r of an n×n matrix; after alltoall each rank
    # holds column r (the transpose semantics of MPI_Alltoall).
    mat = jnp.arange(float(n * n)).reshape(n, n)

    def body(row):
        return comm.alltoall(row, split_axis=1, concat_axis=1)

    f = jax.jit(comm.shard_map(body, in_specs=(comm._world_spec,), out_specs=comm._world_spec))
    out = np.asarray(f(mat))
    np.testing.assert_allclose(out, np.arange(n * n, dtype=np.float64).reshape(n, n).T)


def test_reduce_scatter(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    # Every rank contributes rank-dependent values; each rank ends with its
    # shard of the sum.
    data = jnp.tile(jnp.arange(float(n)), (n, 1))  # rank r holds arange(n)

    def body(x):
        return comm.reduce_scatter(x[0])[None]

    f = jax.jit(comm.shard_map(body, in_specs=(comm._world_spec,), out_specs=comm._world_spec))
    out = np.asarray(f(data))
    for r in range(n):
        np.testing.assert_allclose(out[r].ravel(), [n * r])


def test_ppermute_ring(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        return comm.ppermute(x[0], perm)[None]

    f = jax.jit(comm.shard_map(body, in_specs=(comm._world_spec,), out_specs=comm._world_spec))
    out = np.asarray(f(jnp.arange(float(n)))).ravel()
    # Rank r receives from r-1.
    np.testing.assert_allclose(out, np.roll(np.arange(n), 1))


def _eager_ppermute(comm, perm, data):
    def body(x):
        return comm.ppermute(x[0], perm)[None]

    f = jax.jit(comm.shard_map(
        body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
    ))
    return np.asarray(f(data)).ravel()


def _expected_ppermute(perm, data, n):
    out = np.zeros(n)
    for s, d in perm:
        out[d] = data[s]
    return out


@pytest.mark.parametrize(
    "perm_name",
    ["single_pair", "reverse_pair", "translation", "ring_back", "ring_far",
     "general"],
)
def test_ppermute_flat_rank_semantics(mesh, perm_name):
    """Every lowering tier (per-axis product, uniform shift, all_gather
    fallback) must reproduce flattened-ppermute semantics: perm dsts get
    their src's value, everyone else zeros."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    perms = {
        "single_pair": [(1, n - 2)],
        "reverse_pair": [(n - 1, 0)],
        # grid translation without flat wrap (factors per-axis)
        "translation": [(i, (i + 2) % n) for i in range(0, n, 2)],
        "ring_back": [(i, (i - 1) % n) for i in range(n)],
        # multi-row shift: exercises the q>0 row hop + wrap select
        "ring_far": [(i, (i + 5) % n) for i in range(n)],
        # swap + fixed point: factors on no axis split, exercises fallback
        "general": [(0, n - 3), (1, 2)],
    }
    perm = perms[perm_name]
    data = jnp.arange(1.0, n + 1.0)
    out = _eager_ppermute(comm, perm, data)
    np.testing.assert_allclose(
        out, _expected_ppermute(perm, np.asarray(data), n)
    )


def test_ppermute_multi_axis_avoids_world_gather(devices8):
    """VERDICT r1 item 7: p2p on a 2-axis mesh must move O(message) bytes —
    the lowering decomposes into per-axis ppermute hops; all_gather appears
    only for genuinely non-factoring perms."""
    from chainermn_tpu.communicators import build_mesh

    comm = create_communicator(
        "naive", mesh=build_mesh(inter_size=2, intra_size=4,
                                 devices=devices8)
    )
    n = comm.device_size

    def jaxpr_of(perm):
        def body(x):
            return comm.ppermute(x[0], perm)[None]

        return str(jax.make_jaxpr(comm.shard_map(
            body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
        ))(jnp.arange(float(n))))

    # Single-pair p2p (links.py transfers): <=2 hops, no world gather.
    jx = jaxpr_of([(1, 6)])
    assert "all_gather" not in jx
    assert 1 <= jx.count("ppermute") <= 2
    # Flat ring shift +1 (ring_exchange / pipelines): q=0 so the base row
    # hop is elided — intra hop + wrap row hop = 2, no world gather.
    jx = jaxpr_of([(i, (i + 1) % n) for i in range(n)])
    assert "all_gather" not in jx
    assert jx.count("ppermute") == 2
    # Flat ring shift crossing rows (q=1, r=1): all 3 hops, still O(msg).
    jx = jaxpr_of([(i, (i + 5) % n) for i in range(n)])
    assert "all_gather" not in jx
    assert jx.count("ppermute") == 3
    # Non-factoring perm: documented fallback collapses via all_gather.
    jx = jaxpr_of([(0, 5), (1, 2)])
    assert "all_gather" in jx


def test_ppermute_multi_axis_grad(devices8):
    """The decomposed lowering must stay differentiable: the cotangent of a
    src→dst transfer lands back on src."""
    from chainermn_tpu.communicators import build_mesh

    comm = create_communicator(
        "naive", mesh=build_mesh(inter_size=2, intra_size=4,
                                 devices=devices8)
    )
    n = comm.device_size
    perm = [(2, 7)]

    def loss(data):
        def body(x):
            return comm.ppermute(x[0], perm)[None]

        y = comm.shard_map(
            body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
        )(data)
        return jnp.sum(y * jnp.arange(1.0, n + 1.0))

    g = np.asarray(jax.jit(jax.grad(loss))(jnp.zeros(n)))
    expect = np.zeros(n)
    expect[2] = 8.0  # dst weight (7+1) flows back to src rank 2
    np.testing.assert_allclose(g, expect)


def test_axis_index_order(mesh):
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size

    def body():
        return comm.axis_index()[None]

    f = jax.jit(comm.shard_map(body, in_specs=(), out_specs=comm._world_spec))
    np.testing.assert_array_equal(np.asarray(f()), np.arange(n))


def test_split_subcommunicator(devices8):
    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    sub = comm.split(("intra",))
    assert sub.device_size == 4

    # psum over the intra sub-communicator sums within each mesh row only.
    def body(x):
        return sub.allreduce(x[0], "sum")[None]

    f = jax.jit(
        comm.shard_map(body, in_specs=(P(("inter", "intra")),), out_specs=P(("inter", "intra")))
    )
    out = np.asarray(f(jnp.arange(8.0)))
    np.testing.assert_allclose(out[:4], np.full(4, 0 + 1 + 2 + 3))
    np.testing.assert_allclose(out[4:], np.full(4, 4 + 5 + 6 + 7))


def test_split_hierarchical_degrades_to_flat(devices8):
    from chainermn_tpu.communicators import XlaIciCommunicator

    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    comm = create_communicator("hierarchical", mesh=mesh)
    sub = comm.split(("intra",))
    assert isinstance(sub, XlaIciCommunicator)
    assert sub.device_size == 4


def test_obj_plane_single_process(mesh):
    comm = create_communicator("naive", mesh=mesh)
    assert comm.bcast_obj({"a": 1}) == {"a": 1}
    assert comm.gather_obj("x") == ["x"]
    assert comm.allreduce_obj(3.5) == 3.5
    assert comm.scatter_obj([42]) == 42
    comm.barrier()


def test_p2p_obj_validation(mesh):
    """send_obj/recv_obj reject self/out-of-range peers and, single-process,
    report the missing coordination service instead of hanging.  (The real
    rank0→rank1 transfer runs in tests/_mp_worker.py.)"""
    import pytest

    comm = create_communicator("naive", mesh=mesh)
    with pytest.raises(ValueError, match="send_obj dest"):
        comm.send_obj("x", dest=0)  # self (size==1: no valid peer)
    with pytest.raises(ValueError, match="recv_obj source"):
        comm.recv_obj(source=5)


def test_single_host_rejects_multihost_mesh(devices8):
    from chainermn_tpu.communicators import SingleHostCommunicator

    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    with pytest.raises(ValueError):
        SingleHostCommunicator(mesh)
    ok = build_mesh(inter_size=1, intra_size=8, devices=devices8)
    comm = SingleHostCommunicator(ok)
    assert comm.device_size == 8


def test_unknown_name_raises():
    with pytest.raises(ValueError):
        create_communicator("definitely_not_a_backend")


# ---------------------------------------------------------------------------
# Log-depth point-to-root schedules (binomial tree)
# ---------------------------------------------------------------------------


def test_gather_scatter_log_depth(devices8):
    """The binomial-tree lowerings run in ceil(log2 n) collective rounds:
    at n=8 on a single-axis world, exactly 3 ppermutes each (the previous
    schedule emitted n-1 = 7) and still no all_gather/psum."""
    from chainermn_tpu.communicators import build_mesh

    mesh = build_mesh(inter_size=1, intra_size=8, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size

    def gather_body(xs):
        return comm.gather(xs[0], root=2)[None]

    jx = str(jax.make_jaxpr(
        comm.shard_map(
            gather_body, in_specs=(comm._world_spec,),
            out_specs=comm._world_spec,
        )
    )(jnp.arange(float(n))))
    assert jx.count("ppermute") == 3
    assert "all_gather" not in jx and "psum" not in jx

    def scatter_body(xs):
        return comm.scatter(xs, root=2)[None]

    jx = str(jax.make_jaxpr(
        comm.shard_map(
            scatter_body, in_specs=(P(),), out_specs=comm._world_spec
        )
    )(jnp.arange(float(n * 2))))
    assert jx.count("ppermute") == 3
    assert "all_gather" not in jx and "psum" not in jx


def test_gather_nonzero_root_semantics(mesh):
    """Binomial schedule with a non-zero root: flat-rank stacking order is
    preserved (relative-order blocks are rolled back to flat order)."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    for root in (1, n - 1):
        def body(xs):
            return comm.gather(xs[0] * 10.0, root=root)[None]

        f = jax.jit(comm.shard_map(
            body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
        ))
        out = np.asarray(f(jnp.arange(float(n))))
        np.testing.assert_allclose(out[root], 10.0 * np.arange(n))


def test_eager_gather_root_device_only(mesh):
    """eager_gather returns the stacked result resident ONLY on the root
    device — the off-root-cheap output form."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    x = jax.device_put(
        jnp.arange(float(n * 3)).reshape(n, 3),
        jax.sharding.NamedSharding(comm.mesh, comm._world_spec),
    )
    for root in (0, n - 1):
        out = comm.eager_gather(x, root=root)
        assert isinstance(out.sharding, jax.sharding.SingleDeviceSharding)
        assert next(iter(out.sharding.device_set)) == comm.device_for_rank(root)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_device_for_rank_matches_axis_index(mesh):
    """device_for_rank must invert the traced axis_index flattening."""
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size

    def body(_):
        return comm.axis_index()[None]

    ranks = jax.jit(comm.shard_map(
        body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
    ))(jnp.zeros(n))
    # The traced axis_index value r must live on device_for_rank(r):
    # flat_rank's row-major flattening and the host-side inverse agree.
    for shard in ranks.addressable_shards:
        r = int(np.asarray(shard.data).item())
        assert shard.device == comm.device_for_rank(r), (r, shard.device)


# ---------------------------------------------------------------------------
# MPI_Comm_split: arbitrary subgroups (process plane + device plane)
# ---------------------------------------------------------------------------


def test_split_devices_arbitrary_subsets(devices8):
    """Device-plane split expresses what the axis split cannot: 'every
    4th device' subgroups, each a working communicator over only its own
    devices."""
    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    subs = comm.split_devices([r % 4 for r in range(8)])
    assert sorted(subs) == [0, 1, 2, 3]
    for c, sub in subs.items():
        assert sub.device_size == 2
        got = {d.id for d in sub.mesh.devices.flat}
        want = {devices8[c].id, devices8[c + 4].id}
        assert got == want, (c, got, want)


def test_split_devices_dp_subgroup_within_stage(devices8):
    """A data-parallel subgroup inside one pipeline stage: psum runs over
    ONLY the stage's devices."""
    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    stages = comm.split_devices([r // 4 for r in range(8)])
    for c, sub in stages.items():
        f = jax.jit(sub.shard_map(
            lambda x: jax.lax.psum(x, sub.axes),
            in_specs=(sub._world_spec,), out_specs=P(),
        ))
        out = f(jnp.arange(float(sub.device_size)))
        assert float(np.asarray(out)[0]) == sum(range(sub.device_size))


def test_split_devices_keys_and_undefined(devices8):
    """keys order the subgroup (ties by old rank); None colors are
    MPI_UNDEFINED; wrong-length args raise."""
    mesh = build_mesh(inter_size=1, intra_size=8, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    rev = comm.split_devices([0] * 8, keys=list(range(8))[::-1])[0]
    assert [d.id for d in rev.mesh.devices.flat] == [
        d.id for d in reversed(devices8)
    ]
    subs = comm.split_devices([0, None, None, None, None, None, None, 0])
    assert list(subs) == [0] and subs[0].device_size == 2
    with pytest.raises(ValueError, match="length"):
        comm.split_devices([0, 1])
    with pytest.raises(ValueError, match="length"):
        comm.split_devices([0] * 8, keys=[0])


def test_split_color_single_process(mesh):
    """Process-plane split(color, key) in a single-process world: same
    color returns a whole-world communicator, None is MPI_UNDEFINED."""
    comm = create_communicator("naive", mesh=mesh)
    sub = comm.split(7, key=3)
    assert sub.size == 1 and sub.rank == 0
    assert sub.device_size == comm.device_size
    assert comm.split(None) is None


def test_split_devices_mixed_type_colors(devices8):
    """ADVICE r4: colors are unrestricted by the API, so mixed types
    (int + str) must split cleanly, not raise sorted()'s unordered-types
    TypeError."""
    mesh = build_mesh(inter_size=1, intra_size=8, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    colors = ["a", 0, "a", 0, "a", 0, "a", 0]
    subs = comm.split_devices(colors)
    assert set(subs) == {"a", 0}
    assert subs["a"].device_size == 4 and subs[0].device_size == 4


def test_ppermute_general_fallback_warns_once(devices8):
    """VERDICT r4 weak #2: the all_gather+slice fallback is a silent
    O(world) wire cliff — it must warn (once per process) when it fires."""
    import warnings
    from chainermn_tpu.communicators import base as comm_base

    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    comm = create_communicator("naive", mesh=mesh)
    # swap + fixed point: factors on no axis split -> general fallback
    perm = [(0, 5), (1, 2)]
    data = jnp.arange(1.0, 9.0)
    comm_base._PPERMUTE_FALLBACK_WARNED = False
    try:
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            _eager_ppermute(comm, perm, data)
        hits = [w for w in rec if "all_gather" in str(w.message)]
        assert len(hits) == 1 and hits[0].category is RuntimeWarning
        assert "world-volume" in str(hits[0].message)
        # Second trace: flag already set, no new warning.
        with warnings.catch_warnings(record=True) as rec2:
            warnings.simplefilter("always")
            _eager_ppermute(comm, [(0, 3), (1, 7)], data)
        assert not [w for w in rec2 if "all_gather" in str(w.message)]
        # Factored paths never warn.
        comm_base._PPERMUTE_FALLBACK_WARNED = False
        with warnings.catch_warnings(record=True) as rec3:
            warnings.simplefilter("always")
            _eager_ppermute(comm, [(i, (i + 1) % 8) for i in range(8)], data)
        assert not [w for w in rec3 if "all_gather" in str(w.message)]
    finally:
        comm_base._PPERMUTE_FALLBACK_WARNED = True


@pytest.mark.parametrize("world", [3, 5, 6, 7])
def test_gather_scatter_non_power_of_two_worlds(devices8, world):
    """VERDICT r4 item 4: the binomial gather/scatter padding paths
    (trailing senders ship padding rows when the world is not a power of
    two) must stay value-exact on 3/5/6/7-device worlds — sizes the
    8-device dryruns never see."""
    mesh = build_mesh(inter_size=1, intra_size=world,
                      devices=devices8[:world])
    comm = create_communicator("naive", mesh=mesh)
    n = comm.device_size
    assert n == world

    # gather to every possible root
    for root in (0, n - 1, n // 2):
        def gbody(xs):
            return comm.gather(xs[0], root=root)[None]

        out = np.asarray(jax.jit(comm.shard_map(
            gbody, in_specs=(comm._world_spec,),
            out_specs=comm._world_spec,
        ))(jnp.arange(1.0, n + 1.0)))
        np.testing.assert_allclose(out[root], np.arange(1.0, n + 1.0))
        for r in range(n):
            if r != root:
                np.testing.assert_allclose(out[r], np.zeros(n))

    # scatter from root 0: device r gets its own 2-chunk
    data = jnp.arange(float(n * 2))

    def sbody(xs):
        chunk = comm.scatter(
            jnp.where(comm.axis_index() == 0, xs, 0.0), root=0
        )
        return chunk[None]

    out = np.asarray(jax.jit(comm.shard_map(
        sbody, in_specs=(P(),), out_specs=comm._world_spec,
    ))(data))
    for r in range(n):
        np.testing.assert_allclose(out[r].ravel(), [2 * r, 2 * r + 1])

    # gather gradient: transpose of the padded tree must still route each
    # source exactly its slot's cotangent.
    weights = jnp.arange(1.0, n + 1.0)
    from jax import lax as _lax

    def loss(data):
        def body(xs):
            g = comm.gather(xs[0], root=0)
            contrib = jnp.where(
                comm.axis_index() == 0, jnp.sum(g * weights), 0.0
            )
            return _lax.psum(contrib, comm.axes)[None]

        y = comm.shard_map(
            body, in_specs=(comm._world_spec,), out_specs=comm._world_spec
        )(data)
        return y[0]

    g = np.asarray(jax.jit(jax.grad(loss))(jnp.zeros(n)))
    np.testing.assert_allclose(g, np.asarray(weights))


def test_two_dimensional_inter_leg_bytes_claim(devices8):
    """VERDICT r4 item 8, static form of the 2D bandwidth claim: from the
    traced allreduce_grad jaxpr, the two_dimensional backend's inter-axis
    collective operand bytes must be the flat backend's divided by
    intra_size (its inter psum runs on the reduce_scatter'd shard)."""
    import sys, os
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
    ))
    try:
        from allreduce_bench import (
            assert_two_dimensional_inter_savings,
            bytes_per_leg,
        )
    finally:
        sys.path.pop(0)

    mesh = build_mesh(inter_size=2, intra_size=4, devices=devices8)
    nbytes = 1 << 20
    profiles = {}
    for name in ("flat", "two_dimensional", "hierarchical"):
        comm = create_communicator(name, mesh=mesh)
        profiles[comm.name] = bytes_per_leg(comm, nbytes, jnp.float32)
    # flat: one fused psum over both axes — full payload on each leg.
    assert profiles["flat"]["inter"] == nbytes
    assert profiles["flat"]["intra"] == nbytes
    # two_dimensional: inter leg carries 1/intra of the payload.
    assert profiles["two_dimensional"]["inter"] == nbytes // 4
    # hierarchical: full payload on both legs (two plain psums) — the
    # algorithm two_dimensional improves on for slow inter links.
    assert profiles["hierarchical"]["inter"] == nbytes
    assert_two_dimensional_inter_savings(profiles, intra_size=4)
    # And the assertion actually bites: a wrong ratio must raise.
    bad = dict(profiles)
    bad["two_dimensional"] = {"inter": nbytes, "intra": nbytes}
    with pytest.raises(AssertionError, match="2D bandwidth claim"):
        assert_two_dimensional_inter_savings(bad, intra_size=4)
