"""Golden-file regression test for the backward-OVERLAPPED allreduce
schedule — the companion of ``tests/test_hlo_census_golden.py`` (which
pins the eager emission via ``overlap=False``).

Pins, per communicator, the jaxpr-level census of ``allreduce_grad``
over the canonical 64-leaf tree under the overlapped schedule: the op
counts and reduction totals must be IDENTICAL to the eager golden (the
schedule only reorders emission — no extra collectives per bucket), and
the per-bucket ``op_bytes`` sequence must follow the schedule's reverse
leaf-production bucket order, which is what lets each bucket's
``all-reduce-start`` issue while earlier-leaf gradients are still being
produced.  The schedule itself (bucket emission order, stage shape) is
pinned alongside so an ordering regression fails structurally.

Regenerate after an INTENDED schedule/lowering change::

    python tests/test_overlap_census_golden.py --regen

then review the golden diff like any other code change.
"""

import json
import os

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "allreduce_census_64leaf_overlap.json",
)

#: fixed scenario — matches tests/test_hlo_census_golden.py.
MESH_SHAPE = (2, 4)
N_LEAVES = 64
TOTAL_BYTES = 8 * 1024 * 1024
BUCKET_BYTES = 256 * 1024

COMMUNICATORS = ["naive", "flat", "xla_ici", "hierarchical",
                 "two_dimensional"]


def compute_census() -> dict:
    """The overlapped schedule's census for the pinned scenario (imports
    inside so ``--regen`` can set platform env before jax loads)."""
    import jax

    from chainermn_tpu.communicators import (
        build_mesh,
        build_overlap_schedule,
        create_communicator,
    )
    from chainermn_tpu.communicators.packing import (
        GradPacker,
        synthetic_grad_tree,
    )
    from chainermn_tpu.observability import audit_allreduce_tree

    devs = jax.devices()[: MESH_SHAPE[0] * MESH_SHAPE[1]]
    mesh = build_mesh(
        inter_size=MESH_SHAPE[0], intra_size=MESH_SHAPE[1], devices=devs
    )
    tree = synthetic_grad_tree(N_LEAVES, TOTAL_BYTES)
    packer = GradPacker.for_tree(tree, bucket_bytes=BUCKET_BYTES)
    schedule = build_overlap_schedule(packer, granularity=1)
    out = {
        "mesh": list(MESH_SHAPE),
        "n_leaves": N_LEAVES,
        "total_bytes": TOTAL_BYTES,
        "bucket_bytes": BUCKET_BYTES,
        "n_buckets": packer.n_buckets,
        "schedule": {
            "granularity": schedule.granularity,
            "order": list(schedule.order),
            "stages": [list(s) for s in schedule.stages],
        },
        "communicators": {},
    }
    for name in COMMUNICATORS:
        comm = create_communicator(
            name, mesh=mesh, bucket_bytes=BUCKET_BYTES, overlap=True,
            overlap_granularity=1,
        )
        audit = audit_allreduce_tree(comm, tree)
        out["communicators"][name] = {
            "hlo_collectives": audit.census(),
            "reduction_collectives": audit.reduction_collectives(),
            "per_axis_operand_bytes": dict(
                sorted(audit.bytes_per_axis.items())
            ),
            "op_bytes": {k: list(v) for k, v in
                         sorted(audit.op_bytes.items())},
        }
    return out


def test_overlap_census_matches_golden():
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    current = compute_census()
    for name in COMMUNICATORS:
        assert current["communicators"][name] == \
            golden["communicators"][name], (
                f"{name} overlapped collective census drifted from the "
                f"golden file — if the schedule change is intended, "
                f"regenerate with: python {__file__} --regen"
            )
    assert current == golden


def test_overlap_matches_eager_counts():
    """The ISSUE acceptance bound, as a cross-golden invariant: the
    overlapped schedule emits exactly the eager bucketed counts — same
    collectives per bucket, only the emission order differs."""
    eager_path = os.path.join(
        os.path.dirname(GOLDEN_PATH), "allreduce_census_64leaf.json"
    )
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    with open(eager_path) as f:
        eager = json.load(f)
    for name in COMMUNICATORS:
        ov = golden["communicators"][name]
        eg = eager["communicators"][name]["bucketed"]
        assert ov["hlo_collectives"] == eg["hlo_collectives"]
        assert ov["reduction_collectives"] == eg["reduction_collectives"]
        assert ov["per_axis_operand_bytes"] == eg["per_axis_operand_bytes"]
        # same multiset of per-bucket payloads, schedule-order sequence
        for prim, sizes in ov["op_bytes"].items():
            assert sorted(sizes) == sorted(eg["op_bytes"][prim]), prim


def test_schedule_is_reverse_leaf_production_order():
    """The pinned emission order must be the reverse leaf-production
    order: a bucket whose last leaf appears later in the flatten order
    (produced EARLIER by reverse-mode AD) is emitted first."""
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    from chainermn_tpu.communicators.packing import (
        GradPacker,
        synthetic_grad_tree,
    )

    tree = synthetic_grad_tree(N_LEAVES, TOTAL_BYTES)
    packer = GradPacker.for_tree(tree, bucket_bytes=BUCKET_BYTES)
    order = golden["schedule"]["order"]
    assert sorted(order) == list(range(packer.n_buckets))
    last_leaf = [max(packer.buckets[i].leaf_indices) for i in order]
    assert last_leaf == sorted(last_leaf, reverse=True)


if __name__ == "__main__":
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--regen", action="store_true",
                    help="rewrite the golden file from the current lowering")
    args = ap.parse_args()
    if not args.regen:
        ap.error("run under pytest, or pass --regen to regenerate")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    census = compute_census()
    with open(GOLDEN_PATH, "w") as f:
        json.dump(census, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {GOLDEN_PATH}", file=sys.stderr)
