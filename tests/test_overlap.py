"""Backward-overlapped allreduce schedule tests (communicators/overlap.py
+ the hlo_audit async-pair census it is observed through).

The numerical contract (overlapped == eager, bit-exact, on every
communicator) lives in tests/test_packing.py and the schedule's census
in tests/test_overlap_census_golden.py; this module covers the schedule
builder itself, the env/flag plumbing, the compiled-HLO async-pair
parser (seeded text — CPU compiles never emit start/done pairs, so the
parser cannot be exercised through a live lowering here), and the
recompile-count guard on the staged train step.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from chainermn_tpu.communicators import build_mesh, create_communicator
from chainermn_tpu.communicators.overlap import (
    ENV_OVERLAP,
    OVERLAP_XLA_FLAGS,
    OverlapSchedule,
    build_overlap_schedule,
    ensure_overlap_flags,
    overlap_enabled,
    resolve_granularity,
)
from chainermn_tpu.communicators.packing import (
    GradPacker,
    synthetic_grad_tree,
)


@pytest.fixture(scope="module")
def mesh24(devices8):
    return build_mesh(inter_size=2, intra_size=4, devices=devices8)


# ----------------------------------------------------------------------
# Schedule builder
# ----------------------------------------------------------------------
def test_schedule_reverse_leaf_production_order():
    tree = synthetic_grad_tree(12, 256 * 1024)
    packer = GradPacker.for_tree(tree, bucket_bytes=32 * 1024)
    sched = build_overlap_schedule(packer, granularity=1)

    assert sorted(sched.order) == list(range(packer.n_buckets))
    last = [max(packer.buckets[i].leaf_indices) for i in sched.order]
    assert last == sorted(last, reverse=True)
    assert sched.n_buckets == packer.n_buckets
    assert sched.n_stages == packer.n_buckets  # granularity 1
    assert all(len(s) == 1 for s in sched.stages)


@pytest.mark.parametrize("granularity", [1, 2, 3, 7, 100])
def test_schedule_stage_grouping(granularity):
    tree = synthetic_grad_tree(16, 512 * 1024)
    packer = GradPacker.for_tree(tree, bucket_bytes=64 * 1024)
    sched = build_overlap_schedule(packer, granularity=granularity)

    # Stages partition the same order the granularity-1 schedule emits.
    flat = build_overlap_schedule(packer, granularity=1).order
    assert sched.order == flat
    assert all(len(s) <= granularity for s in sched.stages)
    assert all(len(s) == granularity for s in sched.stages[:-1])
    d = sched.describe()
    assert d["n_buckets"] == packer.n_buckets
    assert d["granularity"] == max(1, granularity)


def test_schedule_empty_and_single_bucket():
    empty = build_overlap_schedule(
        GradPacker.for_tree({}, bucket_bytes=1024)
    )
    assert empty.stages == () and empty.order == ()

    one = build_overlap_schedule(GradPacker.for_tree(
        {"w": np.zeros((64,), np.float32)}, bucket_bytes=1024
    ))
    assert one.order == (0,)


def test_schedule_is_frozen():
    s = OverlapSchedule(stages=((0,),), granularity=1)
    with pytest.raises(Exception):
        s.granularity = 2


# ----------------------------------------------------------------------
# Env gate + XLA flag plumbing
# ----------------------------------------------------------------------
def test_overlap_enabled_gate(monkeypatch):
    monkeypatch.delenv(ENV_OVERLAP, raising=False)
    assert overlap_enabled() is True
    assert overlap_enabled(default=False) is False
    for off in ("0", "false", "OFF", "No", " off "):
        monkeypatch.setenv(ENV_OVERLAP, off)
        assert overlap_enabled() is False
    for on in ("1", "true", "yes", "anything"):
        monkeypatch.setenv(ENV_OVERLAP, on)
        assert overlap_enabled() is True


def test_resolve_granularity_env(monkeypatch):
    monkeypatch.delenv(
        "CHAINERMN_TPU_OVERLAP_GRANULARITY", raising=False
    )
    assert resolve_granularity() == 1
    assert resolve_granularity(default=5) == 5
    monkeypatch.setenv("CHAINERMN_TPU_OVERLAP_GRANULARITY", "4")
    assert resolve_granularity() == 4
    monkeypatch.setenv("CHAINERMN_TPU_OVERLAP_GRANULARITY", "-3")
    assert resolve_granularity() == 1  # clamped
    monkeypatch.setenv("CHAINERMN_TPU_OVERLAP_GRANULARITY", "junk")
    assert resolve_granularity(default=2) == 2


def test_ensure_overlap_flags_appends_once(monkeypatch):
    monkeypatch.delenv(ENV_OVERLAP, raising=False)
    monkeypatch.setenv("XLA_FLAGS", "--xla_dummy=1")
    added = ensure_overlap_flags(force=True)
    assert added == list(OVERLAP_XLA_FLAGS)
    flags = os.environ["XLA_FLAGS"].split()
    assert flags[0] == "--xla_dummy=1"
    assert set(OVERLAP_XLA_FLAGS) <= set(flags)
    # idempotent: a second call adds nothing and changes nothing
    before = os.environ["XLA_FLAGS"]
    assert ensure_overlap_flags(force=True) == []
    assert os.environ["XLA_FLAGS"] == before


def test_ensure_overlap_flags_respects_gates(monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "")
    monkeypatch.setenv(ENV_OVERLAP, "0")
    assert ensure_overlap_flags(force=True) == []  # escape hatch wins

    monkeypatch.setenv(ENV_OVERLAP, "1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert ensure_overlap_flags() == []  # no TPU in play, no force
    assert os.environ["XLA_FLAGS"] == ""

    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    added = ensure_overlap_flags()
    assert added == list(OVERLAP_XLA_FLAGS)


# ----------------------------------------------------------------------
# Compiled-HLO async-pair census (seeded text: only TPU compiles split
# collectives into start/done pairs, so the parser is fed the HLO shape
# the latency-hiding scheduler produces)
# ----------------------------------------------------------------------
_SEEDED_HLO = """\
HloModule overlapped_bwd

ENTRY %main (p0: f32[65536], p1: f32[65536]) -> f32[65536] {
  %p0 = f32[65536]{0} parameter(0)
  %p1 = f32[65536]{0} parameter(1)
  %ars0 = f32[65536]{0} all-reduce-start(%p0), replica_groups={}, to_apply=%sum
  %bwd0 = f32[65536]{0} multiply(%p1, %p1)
  %ard0 = f32[65536]{0} all-reduce-done(%ars0)
  %ars1 = f32[65536]{0} all-reduce-start(%bwd0), replica_groups={}, to_apply=%sum
  %ard1 = f32[65536]{0} all-reduce-done(%ars1)
  %cps = (f32[65536]{0}, f32[65536]{0}) collective-permute-start(%ard0), source_target_pairs={{0,1},{1,0}}
  %bwd1 = f32[65536]{0} add(%ard0, %ard1)
  %cpd = f32[65536]{0} collective-permute-done(%cps)
  ROOT %out = f32[65536]{0} add(%bwd1, %cpd)
}
"""


def test_audit_hlo_text_folds_async_pairs():
    from chainermn_tpu.observability import audit_hlo_text

    audit = audit_hlo_text(_SEEDED_HLO)
    # 2 all-reduce pairs + 1 collective-permute pair = 3 logical
    # collectives, 2 of them reductions; never 6.  (census() is the
    # fixed-key zero-including view — compare the nonzero slice.)
    nonzero = {k: v for k, v in audit.census().items() if v}
    assert nonzero == {"psum": 2, "ppermute": 1}
    assert audit.reduction_collectives() == 2
    assert audit.async_pairs == 3
    # pairs with real compute strictly between start and done: ars0
    # (multiply) and cps (add); ars1 completes immediately -> 2/3.
    assert audit.overlap_fraction == pytest.approx(2 / 3)
    assert audit.op_bytes["psum"] == [65536 * 4, 65536 * 4]
    s = audit.summary()
    assert s["async_pairs"] == 3
    assert s["overlap_fraction"] == pytest.approx(2 / 3)


def test_fold_async_counts():
    from chainermn_tpu.observability import fold_async_counts

    assert fold_async_counts({
        "all-reduce-start": 4, "all-reduce-done": 4, "psum": 1,
    }) == {"psum": 5}
    assert fold_async_counts({
        "reduce-scatter-start": 2, "reduce-scatter-done": 2,
        "all-gather-start": 1, "all-gather-done": 1,
        "collective-permute-start": 3, "collective-permute-done": 3,
    }) == {"reduce_scatter": 2, "all_gather": 1, "ppermute": 3}
    # unmatched done never counts; unmatched start counts once
    assert fold_async_counts({"all-reduce-done": 2}) == {}
    assert fold_async_counts({"all-reduce-start": 2}) == {"psum": 2}


def test_audit_hlo_text_sync_collectives():
    """Plain (unsplit) HLO collectives still census under the jaxpr
    primitive names, with zero pairs."""
    from chainermn_tpu.observability import audit_hlo_text

    hlo = """\
ENTRY %main (p0: f32[128]) -> f32[128] {
  %p0 = f32[128]{0} parameter(0)
  %ar = f32[128]{0} all-reduce(%p0), replica_groups={}, to_apply=%sum
  ROOT %ag = f32[128]{0} all-gather(%ar), dimensions={0}
}
"""
    audit = audit_hlo_text(hlo)
    nonzero = {k: v for k, v in audit.census().items() if v}
    assert nonzero == {"psum": 1, "all_gather": 1}
    assert audit.async_pairs == 0
    assert audit.overlap_fraction == 0.0


def test_audit_compiled_on_cpu_lowering(mesh24):
    """audit_compiled reads a REAL compiled module; on CPU no async
    pairs exist, but the collective counts must match the jaxpr census
    contract (one psum per bucket for xla_ici)."""
    from chainermn_tpu.observability import audit_compiled

    comm = create_communicator(
        "xla_ici", mesh=mesh24, bucket_bytes=32 * 1024
    )
    tree = synthetic_grad_tree(12, 256 * 1024)
    packer = GradPacker.for_tree(tree, bucket_bytes=32 * 1024)
    n = comm.device_size
    stacked = jax.tree.map(
        lambda l: jnp.stack([jnp.asarray(l)] * n), tree
    )

    def fn(t):
        def body(tt):
            sq = jax.tree.map(lambda x: jnp.squeeze(x, 0), tt)
            out = comm.allreduce_grad(sq)
            return jax.tree.map(lambda x: x[None], out)
        spec = jax.tree.map(lambda _: comm._world_spec, t)
        return comm.shard_map(body, in_specs=(spec,), out_specs=spec)(t)

    audit = audit_compiled(fn, stacked)
    assert audit.census().get("psum", 0) == packer.n_buckets
    assert audit.async_pairs == 0  # CPU backend: no start/done pairs


def test_r004_async_fixture_would_flag_unfolded():
    """The regression the fixture pins, shown directly: the raw
    start/done tally (8) crosses R004's >= n_leaves (6) threshold, the
    folded census (4) does not."""
    from chainermn_tpu.analysis.fixtures import (
        _ASYNC_PAIR_HLO,
        fixture_overlap_async_pairs,
    )
    from chainermn_tpu.observability import audit_hlo_text

    t = fixture_overlap_async_pairs()
    audit = t["audit"]
    assert audit.reduction_collectives() == 4 < t["n_leaves"]
    raw = audit_hlo_text(_ASYNC_PAIR_HLO)
    assert raw.async_pairs == 4
    # a double-counting census would have seen start + done = 2 per
    # pair, crossing R004's >= n_leaves threshold
    assert 2 * raw.async_pairs >= t["n_leaves"]


# ----------------------------------------------------------------------
# Staged train step: recompile-count guard
# ----------------------------------------------------------------------
def _leafy_loss(params, batch):
    scale = jnp.mean(batch.astype(jnp.float32) ** 2)
    return scale * sum(
        jnp.vdot(w, w) for w in jax.tree_util.tree_leaves(params)
    )


@pytest.mark.parametrize("overlap", [None, True, False])
def test_staged_step_compiles_once(mesh24, overlap):
    """The staged pipeline must not cost recompiles: after the first
    step establishes the device-resident arg shardings, repeated calls
    reuse one executable (cache size stabilizes, never grows per call)."""
    from chainermn_tpu.optimizers import create_multi_node_optimizer

    comm = create_communicator(
        "xla_ici", mesh=mesh24, bucket_bytes=16 * 1024
    )
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)
    params = {f"w{i}": jnp.ones((32, 32), jnp.float32) for i in range(6)}
    state = opt.init(params)
    step = opt.make_train_step(_leafy_loss, donate=False, overlap=overlap)
    assert hasattr(step, "_cache_size")
    batch = jnp.ones((comm.device_size * 2, 8), jnp.float32)

    params, state, _ = step(params, state, batch)
    warm = step._cache_size()
    for _ in range(3):
        params, state, loss = step(params, state, batch)
        # the first jax-array-input call may add ONE entry over the
        # numpy-input warmup; after that the count must be flat
        assert step._cache_size() <= warm + 1
    assert jnp.isfinite(loss)
    assert step._cache_size() == warm + 1 or step._cache_size() == warm


def test_staged_step_with_state_exposes_cache_size(mesh24):
    from chainermn_tpu.optimizers import create_multi_node_optimizer

    comm = create_communicator("xla_ici", mesh=mesh24)
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)

    def loss_fn(params, mstate, batch):
        return _leafy_loss(params, batch), {"n": mstate["n"] + 1.0}

    step = opt.make_train_step_with_state(loss_fn, donate=False)
    assert hasattr(step, "_cache_size")
    params = {"w": jnp.ones((16, 16), jnp.float32)}
    state = opt.init(params)
    mstate = {"n": jnp.zeros(())}
    out = step(params, state, mstate, jnp.ones((8, 8), jnp.float32))
    params, state, mstate, _ = out
    c1 = step._cache_size()
    step(params, state, mstate, jnp.ones((8, 8), jnp.float32))
    assert step._cache_size() <= c1 + 1


def test_train_step_overlap_pin_is_bit_exact(mesh24):
    """End-to-end: a full train step with overlap pinned ON vs OFF gives
    byte-identical params (the optimizer sees identical averaged
    grads)."""
    from chainermn_tpu.optimizers import create_multi_node_optimizer

    comm = create_communicator(
        "xla_ici", mesh=mesh24, bucket_bytes=16 * 1024
    )
    opt = create_multi_node_optimizer(optax.sgd(0.1), comm)

    def run(overlap):
        params = {
            f"w{i}": jnp.full((32, 32), 0.5 + i, jnp.float32)
            for i in range(6)
        }
        state = opt.init(params)
        step = opt.make_train_step(
            _leafy_loss, donate=False, overlap=overlap
        )
        batch = jnp.arange(
            comm.device_size * 2 * 8, dtype=jnp.float32
        ).reshape(comm.device_size * 2, 8) / 100.0
        params, state, loss = step(params, state, batch)
        return params, loss

    p_on, l_on = run(True)
    p_off, l_off = run(False)
    np.testing.assert_array_equal(np.asarray(l_on), np.asarray(l_off))
    for k in p_on:
        np.testing.assert_array_equal(
            np.asarray(p_on[k]).reshape(-1).view(np.uint8),
            np.asarray(p_off[k]).reshape(-1).view(np.uint8),
            err_msg=k,
        )
