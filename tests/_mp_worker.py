"""Worker for the 2-process jax.distributed harness test.

Run as: python _mp_worker.py <process_id> <num_processes> <coordinator_port>
Prints "MP_WORKER_OK <rank>" on success; any assertion kills the worker.
"""

import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Force the per-process virtual device count (default 4 → the
    # (inter=2, intra=4) deployment shape of SURVEY §2.6: a mesh whose
    # inter leg crosses a REAL process boundary while each process owns
    # several local devices), replacing any inherited
    # host_platform_device_count (pytest's conftest sets 8).
    ndev = int(os.environ.get("CHAINERMN_TPU_TEST_LOCAL_DEVICES", "4"))
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={ndev}")
    os.environ["XLA_FLAGS"] = " ".join(flags)

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert jax.process_index() == pid
    assert jax.device_count() == ndev * nproc

    import numpy as np

    from chainermn_tpu.communicators import create_communicator
    from chainermn_tpu.datasets import scatter_dataset
    from chainermn_tpu.optimizers import create_multi_node_optimizer

    comm = create_communicator("naive")
    # Host-plane topology: one process per "node" (inter row).
    assert comm.rank == pid and comm.size == nproc
    assert comm.device_size == ndev * nproc
    assert comm.inter_size == nproc and comm.intra_size == ndev

    # Object plane across REAL process boundaries (the reference's pickled
    # MPI transport, here over the jax.distributed DCN analogue).
    got = comm.bcast_obj({"payload": [1, 2, 3], "from": "rank0"}, root=0)
    assert got["from"] == "rank0", got

    gathered = comm.gather_obj(("rank", pid))
    assert gathered == [("rank", i) for i in range(nproc)], gathered

    total = comm.allreduce_obj(pid + 1)
    assert total == sum(range(1, nproc + 1)), total

    comm.barrier()

    # scatter_dataset: per-process contiguous shards covering everything.
    shard = scatter_dataset(list(range(10)), comm, shuffle=True, seed=3,
                            force_equal_length=False)
    all_idx = comm.gather_obj(sorted(shard.indices.tolist()))
    merged = sorted(sum(all_idx, []))
    assert merged == list(range(10)), merged

    # broadcast_params: rank-divergent params replicated from process 0.
    import jax.numpy as jnp

    opt = create_multi_node_optimizer(__import__("optax").sgd(0.1), comm)
    params = {"w": jnp.full((3,), float(pid))}
    params = opt.broadcast_params(params)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.0)

    # Full multi-host train step: per-host batches (different data per
    # process, as scatter_dataset produces) assembled into the global batch
    # via comm.global_batch, gradients psum-averaged across ALL processes'
    # devices inside the jitted step.
    params = {"w": jnp.zeros((3,))}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    step = opt.make_train_step(loss_fn)
    state = opt.init(params)
    rng = np.random.RandomState(100 + pid)  # data differs per host
    local = {
        "x": rng.randn(4, 3).astype(np.float32),
        "y": rng.randn(4).astype(np.float32),
    }
    gbatch = comm.global_batch(local)
    assert gbatch["x"].shape == (4 * nproc, 3), gbatch["x"].shape
    params, state, loss = step(params, state, gbatch)
    assert np.isfinite(float(loss)), loss
    # The averaged gradient is identical everywhere → so are the params.
    w_everywhere = comm.gather_obj(np.asarray(params["w"]).tolist())
    for w in w_everywhere[1:]:
        np.testing.assert_allclose(w, w_everywhere[0], rtol=1e-6)

    # Traced binomial-tree gather/scatter whose point-to-root tree spans
    # the REAL process boundary (root on process 1; sources on process 0
    # must relay through the inter leg).  shard_map runs SPMD over the
    # global mesh, so each process verifies its own addressable shards.
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = comm.device_size
    root_rank = n_dev - 1  # last device: owned by the LAST process
    wsharding = NamedSharding(comm.mesh, comm._world_spec)
    src = np.arange(float(n_dev), dtype=np.float32)
    xs_in = jax.make_array_from_callback(
        (n_dev,), wsharding, lambda idx: src[idx]
    )

    def gather_body(xs):
        return comm.gather(xs[0] * 10.0, root=root_rank)[None]

    gout = jax.jit(comm.shard_map(
        gather_body, in_specs=(comm._world_spec,),
        out_specs=comm._world_spec,
    ))(xs_in)
    for shard in gout.addressable_shards:
        r = shard.index[0].start or 0
        if r == root_rank:
            np.testing.assert_allclose(
                np.asarray(shard.data).reshape(-1),
                10.0 * np.arange(n_dev),
            )
    # The root row is addressable exactly on the last process.
    has_root = any(
        (s.index[0].start or 0) == root_rank
        for s in gout.addressable_shards
    )
    assert has_root == (pid == nproc - 1), (pid, has_root)

    full = np.arange(float(2 * n_dev), dtype=np.float32)
    rep = jax.make_array_from_callback(
        (2 * n_dev,), NamedSharding(comm.mesh, P()), lambda idx: full[idx]
    )

    def scatter_body(xs):
        return comm.scatter(xs, root=root_rank)[None]

    sout = jax.jit(comm.shard_map(
        scatter_body, in_specs=(P(),), out_specs=comm._world_spec,
    ))(rep)
    for shard in sout.addressable_shards:
        r = shard.index[0].start or 0
        np.testing.assert_allclose(
            np.asarray(shard.data).reshape(-1), full[2 * r : 2 * r + 2],
        )

    # Multi-host checkpointer: leaves spanning non-addressable devices are
    # saved as per-process shard lists and re-assembled against the
    # template's sharding on load — untestable single-host, the whole
    # point of this harness.
    ckpt_dir = os.environ.get("CHAINERMN_TPU_TEST_CKPT_DIR")
    if ckpt_dir:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from chainermn_tpu.extensions import create_multi_node_checkpointer

        n_dev = comm.device_size
        sh = NamedSharding(comm.mesh, P(("inter", "intra")))
        full = np.arange(n_dev * 3, dtype=np.float32)
        garr = jax.make_array_from_callback(
            (n_dev * 3,), sh, lambda idx: full[idx]
        )
        assert not garr.is_fully_addressable
        cp = create_multi_node_checkpointer("mh", comm, path=ckpt_dir)
        cp.save({"g": garr, "s": jnp.float32(7.0)}, 11)
        loaded, it = cp.maybe_load(
            {"g": garr, "s": jnp.float32(0.0)}
        )
        assert it == 11, it
        assert loaded["g"].sharding == sh
        for s_l, s_o in zip(
            loaded["g"].addressable_shards, garr.addressable_shards
        ):
            np.testing.assert_array_equal(
                np.asarray(s_l.data), np.asarray(s_o.data)
            )
        assert float(loaded["s"]) == 7.0

    # Host-plane point-to-point (reference MpiCommunicatorBase.send/recv):
    # an object moves rank0 → rank1 over the coordination-service KV store
    # with NO world collective — ranks outside the pair do not participate.
    # The second payload spans multiple kvtransport chunks.
    from chainermn_tpu.communicators import kvtransport

    big = np.random.RandomState(7).bytes(2 * kvtransport.CHUNK_BYTES + 12345)
    if pid == 0:
        comm.send_obj({"msg": "hello", "n": 42}, dest=1)
        comm.send_obj(big, dest=1, tag=7)
        assert comm.recv_obj(source=1) == "ack"
    elif pid == 1:
        assert comm.recv_obj(source=0) == {"msg": "hello", "n": 42}
        assert comm.recv_obj(source=0, tag=7) == big
        comm.send_obj("ack", dest=0)

    # Typed ndarray fast path (reference MpiCommunicatorBase moves ndarrays
    # as first-class typed buffers): multi-chunk float32, a 0-d scalar, a
    # non-contiguous view (contiguified on send), and an empty array must
    # all round-trip with exact dtype/shape/values — and arrive as
    # ndarrays, not pickles of them.
    typed = np.random.RandomState(11).randn(
        3 * ((2 * kvtransport.CHUNK_BYTES) // 24) + 3
    ).astype(np.float64)
    if pid == 0:
        comm.send_obj(typed, dest=1, tag=9)
        comm.send_obj(np.array(2.5, np.float32), dest=1, tag=9)
        comm.send_obj(typed.reshape(-1, 3)[:, 1], dest=1, tag=9)  # strided
        comm.send_obj(np.empty((0, 4), np.int16), dest=1, tag=9)
    elif pid == 1:
        got = comm.recv_obj(source=0, tag=9)
        assert isinstance(got, np.ndarray) and got.dtype == np.float64
        np.testing.assert_array_equal(got, typed)
        got = comm.recv_obj(source=0, tag=9)
        assert isinstance(got, np.ndarray)
        assert got.shape == () and got.dtype == np.float32
        assert float(got) == 2.5
        got = comm.recv_obj(source=0, tag=9)
        np.testing.assert_array_equal(got, typed.reshape(-1, 3)[:, 1])
        got = comm.recv_obj(source=0, tag=9)
        assert got.shape == (0, 4) and got.dtype == np.int16

    # Same matrix over the KV chunk fallback plane (the path used where
    # direct TCP is unavailable): flip the plane on BOTH processes in SPMD
    # order, round-trip typed + pickled payloads, flip back.
    kvtransport.ObjectPlane._use_sockets = False
    try:
        if pid == 0:
            comm.send_obj(typed, dest=1, tag=13)
            comm.send_obj({"via": "kv"}, dest=1, tag=13)
        elif pid == 1:
            got = comm.recv_obj(source=0, tag=13)
            assert isinstance(got, np.ndarray)
            np.testing.assert_array_equal(got, typed)
            assert comm.recv_obj(source=0, tag=13) == {"via": "kv"}
    finally:
        kvtransport.ObjectPlane._use_sockets = True

    # scatter_obj is point-to-point under the KV plane: each rank receives
    # exactly its own element from root.
    items = [f"item{r}" for r in range(nproc)] if pid == 0 else None
    assert comm.scatter_obj(items, root=0) == f"item{pid}"

    # Communicator matrix across REAL process boundaries: every variant's
    # inter (DCN) collective leg, with fp32 and bf16 wire dtypes, must
    # reproduce the naive oracle's trajectory.
    import optax

    def run_steps(comm2, nsteps=2):
        opt2 = create_multi_node_optimizer(optax.sgd(0.1), comm2)
        p = {"w": jnp.zeros((3,))}
        st = opt2.init(p)
        stp = opt2.make_train_step(loss_fn, donate=False)
        gb = comm2.global_batch(local)
        for _ in range(nsteps):
            p, st, _ = stp(p, st, gb)
        return np.asarray(p["w"].addressable_shards[0].data).reshape(-1)

    ref_w = run_steps(comm)
    for name in ("xla_ici", "hierarchical", "two_dimensional"):
        for wire in (None, "bfloat16"):
            c2 = create_communicator(name, allreduce_grad_dtype=wire)
            w = run_steps(c2)
            tol = 1e-6 if wire is None else 6e-2
            np.testing.assert_allclose(
                w, ref_w, rtol=tol, atol=tol, err_msg=f"{name} wire={wire}"
            )

    # ZeRO-3 across a real process boundary: master params sharded over all
    # devices of both processes (w has 3 elements over 4 devices → the
    # padded-shard path), trajectory must match the replicated optimizer.
    zcomm = create_communicator("xla_ici")
    zopt = create_multi_node_optimizer(optax.sgd(0.1), zcomm, zero_stage=3)
    p0 = {"w": jnp.zeros((3,))}
    zstate = zopt.init(p0)
    flat = zopt.shard_params(p0)
    zstep = zopt.make_train_step(loss_fn, donate=False)
    zgb = zcomm.global_batch(local)
    for _ in range(2):
        flat, zstate, zloss = zstep(flat, zstate, zgb)
    zw = np.asarray(
        zopt.materialize(flat)["w"].addressable_shards[0].data
    ).reshape(-1)
    np.testing.assert_allclose(zw, ref_w, rtol=1e-5, atol=1e-6)
    assert np.isfinite(float(zloss))

    from jax import lax

    # MPI_Comm_split(color, key) across REAL process boundaries
    # (REF:chainermn/communicators/mpi_communicator_base.py split).
    # Disjoint colors: every process its own singleton subgroup whose
    # mesh holds ONLY its local devices.
    solo = comm.split(pid)
    assert solo.size == 1 and solo.rank == 0
    assert solo.device_size == ndev
    assert all(
        d.process_index == pid for d in solo.mesh.devices.flat
    )
    # Same color, reversed keys: subgroup rank order flips.
    rev = comm.split(0, key=nproc - pid)
    assert rev.size == nproc
    assert rev.rank == nproc - 1 - pid, (rev.rank, pid)
    # Subgroup object plane: root is the subgroup's rank 0 = global
    # LAST process; payload visible to all members.
    got = rev.bcast_obj(("from", pid) if rev.rank == 0 else None, root=0)
    assert got == ("from", nproc - 1), got
    # Subgroup allgather is ordered by subgroup rank (key order).
    ag = rev.allgather_obj(pid)
    assert ag == list(range(nproc))[::-1], ag
    # Point-to-root gather_obj: list at root only, None elsewhere.
    g = rev.gather_obj(f"p{pid}", root=0)
    if rev.rank == 0:
        assert g == [f"p{r}" for r in reversed(range(nproc))], g
    else:
        assert g is None
    rev.barrier()
    # Subgroup DEVICE plane: the sub-mesh's inter rows follow key order
    # (last process first); a psum over it must still see every device.
    tot = jax.jit(rev.shard_map(
        lambda x: lax.psum(x, rev.axes),
        in_specs=(rev._world_spec,), out_specs=jax.sharding.PartitionSpec(),
    ))(jax.make_array_from_callback(
        (rev.device_size,),
        NamedSharding(rev.mesh, rev._world_spec),
        lambda idx: np.arange(float(rev.device_size), dtype=np.float32)[idx],
    ))
    np.testing.assert_allclose(
        float(tot.addressable_shards[0].data.reshape(-1)[0]),
        sum(range(rev.device_size)),
    )
    # MPI_UNDEFINED on one process only: plane ordinals stay in lockstep,
    # so a later world communicator still lines up across processes.
    maybe = comm.split(0 if pid == 0 else None)
    if pid == 0:
        assert maybe.size == 1
    else:
        assert maybe is None
    after = create_communicator("naive")
    assert after.bcast_obj({"post": "split"}, root=0)["post"] == "split"

    # Reporter cross-host aggregation over the REAL multi-process object
    # plane: rank-dependent observations must merge to the same
    # observation-weighted totals on every rank.
    from chainermn_tpu.observability import Reporter

    rep = Reporter()
    rep.observe("loss", float(pid))       # one observation per rank
    rep.observe("loss", float(pid) + 1.0)
    rep.count("steps", pid + 1)
    rep.histogram_observe("lat", 2.0 ** pid)
    agg = rep.aggregate(after)
    n = after.size
    loss = agg["scalars"]["loss"]
    assert loss["count"] == 2 * n, loss
    # sum over ranks of (pid + pid+1) = 2*sum(pid) + n
    assert loss["sum"] == float(n * (n - 1) + n), loss
    assert loss["min"] == 0.0 and loss["max"] == float(n), loss
    assert agg["counters"]["steps"] == n * (n + 1) // 2, agg["counters"]
    # 2^pid lands in bucket pid (ceil(log2) with 2^0=1 -> bucket 0).
    assert sum(agg["histograms"]["lat"].values()) == n, agg["histograms"]

    print(f"MP_WORKER_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
