"""On-TPU test tier (SURVEY §4: the reference gated GPU-only tests with
``@attr.gpu`` markers run on GPU CI; this is the TPU counterpart).

The suite's conftest forces the virtual CPU mesh in-process, so these
tests spawn SUBPROCESSES with the *default* environment — the axon/TPU
plugin active — and skip cleanly when no real chip answers.  They assert
the COMPILED (non-interpret) Pallas kernel path and a real train step on
the chip, which bench.py only ever times.
"""

import functools
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_on_tpu_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tpu_env():
    env = dict(os.environ)
    # Undo the CPU forcing the test process may have exported; keep the
    # axon plugin trigger (PALLAS_AXON_POOL_IPS) exactly as the container
    # set it.
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split() if "host_platform_device_count" not in f
    )
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_REPO, env.get("PYTHONPATH")) if p
    )
    return env


def _run(args, timeout):
    return subprocess.run(
        [sys.executable, _WORKER, *args],
        env=_tpu_env(), capture_output=True, text=True, timeout=timeout,
    )


@functools.cache
def _tpu_available() -> bool:
    try:
        r = _run(["probe"], timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return r.returncode == 0 and r.stdout.strip() in ("tpu", "axon")


def _require_tpu():
    if not _tpu_available():
        pytest.skip("no real TPU/axon device (probe subprocess)")


def test_flash_attention_compiled_on_tpu():
    """The compiled Mosaic kernel (fwd + explicit-vjp bwd) must match the
    XLA oracle ON THE CHIP — interpret-mode agreement (the CPU suite)
    does not cover Mosaic lowering."""
    _require_tpu()
    r = _run(["flash"], timeout=900)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr[-4000:]}"
    assert "OK" in r.stdout


def test_train_step_chip_matches_cpu():
    """One real data-parallel train-step trajectory on the chip must match
    the same trajectory computed on CPU (fp32, 3 steps)."""
    _require_tpu()
    r_tpu = _run(["trainstep"], timeout=900)
    assert r_tpu.returncode == 0, r_tpu.stderr[-4000:]

    env = _tpu_env()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    r_cpu = subprocess.run(
        [sys.executable, _WORKER, "trainstep"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r_cpu.returncode == 0, r_cpu.stderr[-4000:]

    def losses(out):
        return [
            float(line.split(":")[1]) for line in out.splitlines()
            if line.startswith("loss ")
        ]

    lt, lc = losses(r_tpu.stdout), losses(r_cpu.stdout)
    assert len(lt) == len(lc) == 3, (r_tpu.stdout, r_cpu.stdout)
    for a, b in zip(lt, lc):
        assert abs(a - b) <= 1e-5 * max(1.0, abs(b)), (lt, lc)
