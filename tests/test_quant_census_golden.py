"""Golden-file regression test for the QUANTIZED allreduce census.

Pins the jaxpr-level collective lowering of ``allreduce_grad`` under
``comm_dtype="int8"`` over the same canonical 64-leaf tree as
``test_hlo_census_golden.py``: the scaled wire must still emit <= 2
reduction collectives per dtype bucket (the amax agreement rides a
``pmax``, which is NOT a payload reduction and must not inflate the
census), and the reduction payload itself must narrow to one byte per
element.  A refactor that silently de-fuses the quantized path into
per-leaf reductions, or that starts counting the scale exchange as
payload, fails here with a structural diff.

Regenerate after an INTENDED lowering change::

    python tests/test_quant_census_golden.py --regen
"""

import json
import os

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "allreduce_census_64leaf_int8.json",
)
BASELINE_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "allreduce_census_64leaf.json",
)

#: fixed scenario — matches tests/test_hlo_census_golden.py so the
#: quantized census is directly comparable to the full-precision one.
MESH_SHAPE = (2, 4)
N_LEAVES = 64
TOTAL_BYTES = 8 * 1024 * 1024
BUCKET_BYTES = 256 * 1024

COMMUNICATORS = ["naive", "flat", "xla_ici", "hierarchical",
                 "two_dimensional"]


def compute_census() -> dict:
    import jax

    from chainermn_tpu.communicators import build_mesh, create_communicator
    from chainermn_tpu.communicators.packing import synthetic_grad_tree
    from chainermn_tpu.observability import audit_allreduce_tree

    devs = jax.devices()[: MESH_SHAPE[0] * MESH_SHAPE[1]]
    mesh = build_mesh(
        inter_size=MESH_SHAPE[0], intra_size=MESH_SHAPE[1], devices=devs
    )
    tree = synthetic_grad_tree(N_LEAVES, TOTAL_BYTES)
    out = {
        "mesh": list(MESH_SHAPE),
        "n_leaves": N_LEAVES,
        "total_bytes": TOTAL_BYTES,
        "bucket_bytes": BUCKET_BYTES,
        "comm_dtype": "int8",
        "communicators": {},
    }
    for name in COMMUNICATORS:
        comm = create_communicator(
            name, mesh=mesh, bucket_bytes=BUCKET_BYTES, overlap=False,
            comm_dtype="int8",
        )
        audit = audit_allreduce_tree(comm, tree)
        out["communicators"][name] = {
            "hlo_collectives": audit.census(),
            "reduction_collectives": audit.reduction_collectives(),
            "per_axis_operand_bytes": dict(
                sorted(audit.bytes_per_axis.items())
            ),
            "op_bytes": {k: list(v) for k, v in
                         sorted(audit.op_bytes.items())},
        }
    return out


def test_quantized_census_matches_golden():
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    current = compute_census()
    for name in COMMUNICATORS:
        assert current["communicators"][name] == \
            golden["communicators"][name], (
                f"{name} quantized collective census drifted from the "
                f"golden file — if the lowering change is intended, "
                f"regenerate with: python {__file__} --regen"
            )
    assert current == golden


def test_quantized_golden_internal_consistency():
    """The pinned numbers must satisfy the acceptance bounds: <= 2
    reduction collectives per bucket (scale exchange excluded), and the
    reduction payload narrowed vs the full-precision golden."""
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)
    with open(BASELINE_GOLDEN_PATH) as f:
        baseline = json.load(f)
    from chainermn_tpu.communicators.packing import (
        GradPacker,
        synthetic_grad_tree,
    )

    tree = synthetic_grad_tree(N_LEAVES, TOTAL_BYTES)
    plan = GradPacker.for_tree(tree, bucket_bytes=BUCKET_BYTES)
    assert plan.n_leaves == N_LEAVES
    for name, entry in golden["communicators"].items():
        assert entry["reduction_collectives"] <= 2 * plan.n_buckets, name
        # quantizing must not change HOW MANY payload reductions run —
        # only what rides them (int8 instead of fp32)...
        base = baseline["communicators"][name]["bucketed"]
        assert entry["reduction_collectives"] == \
            base["reduction_collectives"], name
        # ...so the per-axis reduction traffic shrinks.  Not a strict
        # 4x: the fp32 amax scalars and any fp32 residual ops ride the
        # same axes, but the narrowing must dominate.
        for axis, b in entry["per_axis_operand_bytes"].items():
            assert b < base["per_axis_operand_bytes"][axis], (name, axis)


if __name__ == "__main__":
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--regen", action="store_true",
                    help="rewrite the golden file from the current lowering")
    args = ap.parse_args()
    if not args.regen:
        ap.error("run under pytest, or pass --regen to regenerate")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    census = compute_census()
    with open(GOLDEN_PATH, "w") as f:
        json.dump(census, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {GOLDEN_PATH}", file=sys.stderr)
