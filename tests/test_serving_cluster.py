"""Multi-replica serving tier: router, migration, disaggregation,
failover health.

The cluster-level contract extends the single-engine one from
tests/test_serving.py:

1. **Bit-exact routing** — a token stream is identical whether a
   request runs alone through ``engine.generate``, shares one
   replica's continuous batch, or crosses replicas (failover re-prefill
   from the committed prefix, prefill→decode KV-page migration).
   Counter-based sampling makes the stream a pure function of
   ``(prompt, committed prefix, position)``.
2. **KV conservation across migration** — extract + restore moves a
   live sequence between pools with ``assert_consistent`` holding on
   both sides and the pages bit-equal over the wire.
3. **Load-aware placement** — the router spreads decode work, honors
   roles/draining/watermark admissibility, and propagates the
   frontend's throughput-derived retry-after hint when every queue is
   full.
4. **Liveness** — heartbeat death detection re-queues exactly the dead
   replica's in-flight requests; survivors never see corrupted state.

All CPU, in-process (threads at most).  The cross-process service loop
soaks in tests/test_multiprocess.py.
"""

import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.serving import (
    EngineConfig,
    InferenceEngine,
    OutOfBlocks,
    QueueFull,
    Request,
    SamplingParams,
    prompt_digests,
)
from chainermn_tpu.serving.cluster import (
    HeartbeatMonitor,
    Replica,
    ReplicaRouter,
    ThreadedClusterDriver,
    extract_sequence,
    recv_snapshot,
    restore_sequence,
    scale_signals,
    send_snapshot,
)

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    from chainermn_tpu.models.transformer import TransformerLM

    return TransformerLM(vocab=VOCAB, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def lm_params(lm):
    return lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


def make_engine(lm, lm_params, **over):
    cfg = dict(block_size=4, n_blocks=64, max_len=64, max_batch=4)
    cfg.update(over)
    return InferenceEngine(lm, lm_params, EngineConfig(**cfg))


def prompts_for(n, rng_seed=7, lo=3, hi=13):
    rng = np.random.default_rng(rng_seed)
    return [
        [int(t) for t in rng.integers(0, VOCAB, size=int(l))]
        for l in rng.integers(lo, hi, size=n)
    ]


def oracle_streams(lm, lm_params, prompts, n):
    """Sequential single-engine reference — a FRESH engine per call so
    no cluster state can leak into the baseline."""
    eng = make_engine(lm, lm_params)
    return [eng.generate(p, n) for p in prompts]


# ---------------------------------------------------------------------------
# Unit seams: seq_len, adopt_request, retry-after hint
# ---------------------------------------------------------------------------


def test_kv_seq_len_tracks_allocation(lm, lm_params):
    eng = make_engine(lm, lm_params)
    eng.kv.allocate("s", 6)
    assert eng.kv.seq_len("s") == 6
    eng.kv.extend("s", 9)
    assert eng.kv.seq_len("s") == 9
    eng.kv.free("s")
    with pytest.raises(KeyError):
        eng.kv.seq_len("s")


def test_adopt_request_validates_cache_state(lm, lm_params):
    from chainermn_tpu.serving import ContinuousBatchingScheduler

    eng = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(eng)
    req = Request(request_id="r", prompt=[1, 2, 3], max_new_tokens=4)
    req.generated = [5]
    # no pages for "r" at all
    with pytest.raises(ValueError):
        sched.adopt_request(req)
    # pages covering the wrong number of positions
    eng.kv.allocate("r", 2)
    with pytest.raises(ValueError):
        sched.adopt_request(req)
    eng.kv.extend("r", len(req.context) - 1)
    sched.adopt_request(req)
    assert req in sched.running
    # adoption is batch-capacity bounded (retryable, not terminal)
    for i in range(eng.max_batch - 1):
        sched.running.append(
            Request(request_id=i, prompt=[1], max_new_tokens=1)
        )
    r2 = Request(request_id="r2", prompt=[1, 2], max_new_tokens=4)
    eng.kv.allocate("r2", 1)
    with pytest.raises(OutOfBlocks):
        sched.adopt_request(r2)


def test_adopted_request_stream_is_bit_exact(lm, lm_params):
    """Adoption = exactly the state a locally-running request has
    between iterations: prefill by hand, adopt, finish — stream matches
    the sequential engine."""
    from chainermn_tpu.serving import ContinuousBatchingScheduler

    prompt = prompts_for(1)[0]
    [want] = oracle_streams(lm, lm_params, [prompt], 6)

    eng = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(eng)
    req = Request(request_id="a", prompt=prompt, max_new_tokens=6)
    eng.kv.allocate("a", len(prompt))
    logits = eng.prefill(prompt, "a")
    req.generated = [eng.sample(logits, req.sampling, len(prompt))]
    sched.adopt_request(req)
    sched.run_to_completion()
    assert req.generated == want


def test_frontend_retry_after_hint_from_throughput(lm, lm_params):
    from chainermn_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeFrontend,
    )

    fe = ServeFrontend(
        ContinuousBatchingScheduler(make_engine(lm, lm_params)),
        max_queue=2,
    )
    p = prompts_for(1)[0]
    # cold: no throughput estimate yet, hint is None
    fe.submit(p, 8)
    fe.submit(p, 8)
    with pytest.raises(QueueFull) as e1:
        fe.submit(p, 8)
    assert e1.value.retry_after_s is None
    assert fe.decode_tokens_per_sec() is None
    for _ in range(4):
        fe.step()
    assert fe.decode_tokens_per_sec() > 0
    fe.submit(p, 8)   # the first two are running now; queue refills
    fe.submit(p, 8)
    with pytest.raises(QueueFull) as e2:
        fe.submit(p, 8)
    assert e2.value.retry_after_s > 0
    assert "retry after" in str(e2.value)
    fe.run_until_idle()


# ---------------------------------------------------------------------------
# Router: load-aware placement, parity, backpressure
# ---------------------------------------------------------------------------


def _mk_cluster(lm, lm_params, n=2, roles=None, **router_kw):
    reps = [
        Replica(i, make_engine(lm, lm_params),
                role=(roles[i] if roles else "both"),
                max_queue=router_kw.pop(f"_q{i}", 8))
        for i in range(n)
    ]
    return reps, ReplicaRouter(reps, **router_kw)


def test_router_parity_and_load_spread(lm, lm_params):
    prompts = prompts_for(6, rng_seed=3)
    want = oracle_streams(lm, lm_params, prompts, 8)
    reps, router = _mk_cluster(lm, lm_params, n=2)
    handles = [router.submit(p, 8) for p in prompts]
    router.run_until_idle()
    for h, w in zip(handles, want):
        assert h.status == "finished"
        assert router.result(h) == w
    # load-aware scoring spreads concurrent work over both replicas
    assert {h.replica_id for h in handles} == {0, 1}
    for r in reps:
        r.engine.kv.assert_consistent()


def test_router_respects_draining_and_roles(lm, lm_params):
    reps, router = _mk_cluster(lm, lm_params, n=2)
    router.drain(0)
    h = router.submit(prompts_for(1)[0], 4)
    router.run_until_idle()
    assert h.replica_id == 1
    # prefill-only replicas never take decode placements
    reps2, router2 = _mk_cluster(lm, lm_params, n=2,
                                 roles=["prefill", "both"])
    h2 = router2.submit(prompts_for(1)[0], 4)
    router2.run_until_idle()
    assert h2.replica_id == 1


def test_router_queue_full_propagates_min_hint(lm, lm_params):
    reps = [Replica(0, make_engine(lm, lm_params, max_batch=1),
                    max_queue=1)]
    router = ReplicaRouter(reps)
    p = prompts_for(1)[0]
    router.submit(p, 8)
    with pytest.raises(QueueFull):
        router.submit(p, 8)
    router.run_until_idle()


def test_router_failover_is_bit_exact(lm, lm_params):
    """Kill a replica mid-stream: its requests re-place on the
    survivor with the committed prefix replayed — streams stay
    bit-identical to the sequential oracle and the survivor's cache
    invariants hold."""
    prompts = prompts_for(6, rng_seed=11, lo=4, hi=10)
    want = oracle_streams(lm, lm_params, prompts, 8)
    reps, router = _mk_cluster(
        lm, lm_params, n=2,
        health=HeartbeatMonitor([0, 1], miss_after_s=1e9),
    )
    handles = [router.submit(p, 8) for p in prompts]
    for _ in range(3):  # some tokens committed on both replicas
        router.step()
    victim = next(h.replica_id for h in handles if not h.done)
    survivor = 1 - victim
    requeued = router.fail_replica(victim, "test kill")
    assert requeued > 0
    router.run_until_idle()
    for h, w in zip(handles, want):
        assert h.status == "finished"
        assert h.tokens == w
    assert any(h.failovers == 1 for h in handles)
    assert all(
        h.replica_id == survivor for h in handles if h.failovers
    )
    reps[survivor].engine.kv.assert_consistent()


def test_cluster_handle_timeout_and_result(lm, lm_params):
    clock = [0.0]
    reps = [Replica(0, make_engine(lm, lm_params),
                    clock=lambda: clock[0])]
    router = ReplicaRouter(reps, clock=lambda: clock[0])
    h = router.submit(prompts_for(1)[0], 8, timeout_s=5.0)
    router.step()
    clock[0] = 10.0
    router.step()
    assert h.status == "timeout"
    with pytest.raises(TimeoutError):
        router.result(h)


# ---------------------------------------------------------------------------
# Migration: extract/restore, wire roundtrip
# ---------------------------------------------------------------------------


def test_migration_mid_stream_is_bit_exact(lm, lm_params):
    """Move a live sequence to a DIFFERENTLY-SIZED pool mid-decode and
    finish there — the stream equals the sequential oracle's."""
    prompt = prompts_for(1, rng_seed=5)[0]
    [want] = oracle_streams(lm, lm_params, [prompt], 8)

    src = make_engine(lm, lm_params)
    dst = make_engine(lm, lm_params, n_blocks=32)
    sp = SamplingParams()
    src.kv.allocate("s", len(prompt))
    logits = src.prefill(prompt, "s")
    toks = [src.sample(logits, sp, len(prompt))]
    cur = len(prompt)
    for _ in range(3):
        src.kv.extend("s", cur + 1)
        logits = src.decode([toks[-1]], ["s"], [cur])[0]
        cur += 1
        toks.append(src.sample(logits, sp, cur))

    snap = extract_sequence(src, "s", context=prompt + toks[:-1])
    assert snap.seq_len == cur and snap.n_pages > 0
    src.kv.free("s")
    src.kv.assert_consistent()

    restore_sequence(dst, snap, "t")
    dst.kv.assert_consistent()
    while len(toks) < 8:
        dst.kv.extend("t", cur + 1)
        logits = dst.decode([toks[-1]], ["t"], [cur])[0]
        cur += 1
        toks.append(dst.sample(logits, sp, cur))
    assert toks == want


def test_restore_rejects_mismatched_geometry(lm, lm_params):
    src = make_engine(lm, lm_params)
    src.kv.allocate("s", 5)
    src.prefill([1, 2, 3, 4, 5], "s")
    snap = extract_sequence(src, "s")
    bad = make_engine(lm, lm_params, block_size=8, n_blocks=32)
    with pytest.raises(ValueError):
        restore_sequence(bad, snap, "t")
    bad.kv.assert_consistent()  # failed restore leaks nothing


def test_snapshot_socket_roundtrip(monkeypatch):
    """KV snapshot over a REAL loopback SocketPlane pair: typed frames,
    dtype/shape/bit-equal pages, context intact."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_kvtransport import FakeKvClient

    from chainermn_tpu.communicators import kvtransport as kvt
    from chainermn_tpu.serving.cluster.migration import KVSnapshot

    fake = FakeKvClient()
    monkeypatch.setattr(kvt, "client", lambda: fake)
    p0, p1 = kvt.SocketPlane(0), kvt.SocketPlane(1)

    class MiniPlane:
        """ObjectPlane-shaped shim over a raw SocketPlane."""

        def __init__(self, sp, rank):
            self.sp, self.rank, self.members = sp, rank, [0, 1]
            self._seq = {}

        def send(self, obj, dest, tag=0):
            k = ("s", dest, tag)
            self.sp.send("mig", dest, tag, self._seq.get(k, 0), obj)
            self._seq[k] = self._seq.get(k, 0) + 1

        def recv(self, src, tag=0, timeout_ms=None):
            k = ("r", src, tag)
            out = self.sp.recv("mig", src, tag, self._seq.get(k, 0),
                               timeout_ms=timeout_ms)
            self._seq[k] = self._seq.get(k, 0) + 1
            return out

    rng = np.random.default_rng(0)
    snap = KVSnapshot(
        seq_len=7, block_size=4,
        pages=[
            rng.standard_normal((2, 4, 2, 8)).astype(np.float32),
            rng.standard_normal((2, 4, 2, 8)).astype(np.float32),
        ],
        context=[1, 2, 3, 4, 5, 6, 7],
    )
    got = []
    t = threading.Thread(
        target=lambda: got.append(
            recv_snapshot(MiniPlane(p1, 1), 0, timeout_ms=10_000)
        )
    )
    t.start()
    send_snapshot(MiniPlane(p0, 0), 1, snap)
    t.join(10)
    assert got and got[0].seq_len == 7
    assert got[0].context == snap.context
    for a, b in zip(got[0].pages, snap.pages):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# Disaggregation: prefill role never decodes, decoders never prefill long
# ---------------------------------------------------------------------------


def test_disagg_prefill_decode_split(lm, lm_params):
    long_prompt = prompts_for(1, rng_seed=9, lo=24, hi=25)[0]
    short = prompts_for(3, rng_seed=10, lo=3, hi=6)
    want = oracle_streams(
        lm, lm_params, [long_prompt] + short, 8
    )
    reps, router = _mk_cluster(
        lm, lm_params, n=2, roles=["prefill", "decode"],
        prefill_threshold=10,
    )
    handles = [router.submit(long_prompt, 8)]
    handles += [router.submit(p, 8) for p in short]
    router.run_until_idle()
    for h, w in zip(handles, want):
        assert h.status == "finished"
        assert h.tokens == w
    # the long prompt decoded on the decode replica, and the prefill
    # replica never ran a decode step
    assert handles[0].replica_id == 1
    assert reps[0].engine._tokens_decoded == 0
    # short prompts bypassed the prefill tier entirely
    assert all(h.replica_id == 1 for h in handles[1:])
    for r in reps:
        r.engine.kv.assert_consistent()


def test_disagg_requeues_when_prompt_cannot_fit(lm, lm_params):
    """A prompt larger than the prefill pool is a terminal error, not a
    hang; one that merely doesn't fit RIGHT NOW re-queues behind the
    pool."""
    from chainermn_tpu.serving.cluster.disagg import (
        PrefillJob,
        run_prefill_job,
    )

    eng = make_engine(lm, lm_params, n_blocks=4)  # 16 token positions
    res = run_prefill_job(eng, PrefillJob(
        handle=0, prompt=list(range(1, 30)), sampling=SamplingParams(),
    ))
    assert res is not None and res.error is not None
    # transiently full: pages held by another sequence
    eng2 = make_engine(lm, lm_params, n_blocks=4)
    eng2.kv.allocate("hog", 12)
    out = run_prefill_job(eng2, PrefillJob(
        handle=1, prompt=list(range(1, 9)), sampling=SamplingParams(),
    ))
    assert out is None  # requeue signal
    eng2.kv.free("hog")
    out = run_prefill_job(eng2, PrefillJob(
        handle=1, prompt=list(range(1, 9)), sampling=SamplingParams(),
    ))
    assert out is not None and out.error is None
    assert out.snapshot.n_pages == 2
    eng2.kv.assert_consistent()  # scratch freed either way


# ---------------------------------------------------------------------------
# Cluster-global prefix index (gossip)
# ---------------------------------------------------------------------------


def test_prefix_digest_content_addressed_and_defrag_stable():
    """Digests are a pure function of the token run — platform-width
    independent, and untouched by defragmentation (defrag rewrites
    page VALUES; the index keys are token runs)."""
    from chainermn_tpu.serving import PagedKVCache, prefix_digest, \
        prompt_digests

    toks = list(range(12))
    d1 = prefix_digest(toks)
    assert d1 == prefix_digest(tuple(toks))
    assert d1 == prefix_digest(np.asarray(toks, np.int32))
    assert d1 != prefix_digest(toks[:-1])
    assert prompt_digests(toks, 4) == [
        prefix_digest(toks[:4]), prefix_digest(toks[:8]),
        prefix_digest(toks),
    ]
    assert prompt_digests(toks[:3], 4) == []     # no full page
    kv = PagedKVCache(16, 4)
    kv.allocate("a", 12)
    kv.register_prefix("a", toks)
    before = kv.prefix_digests()
    kv.free("a")
    kv.defragment()
    assert kv.prefix_digests() == before
    assert kv.match_prefix(toks)                 # index still serves


def test_prefix_digest_tenant_salt_isolates_namespaces():
    """The tenant namespace salts the digest AND the index key: the
    same token run digests differently per namespace, None reproduces
    the historical unsalted digest, and a registration in one namespace
    never matches from another — per-tenant prefix isolation is
    content-addressing, not an ACL bolted on top."""
    from chainermn_tpu.serving import PagedKVCache, prefix_digest, \
        prompt_digests

    toks = list(range(12))
    assert prefix_digest(toks) == prefix_digest(toks, namespace=None)
    da, db = prefix_digest(toks, "ta"), prefix_digest(toks, "tb")
    assert len({prefix_digest(toks), da, db}) == 3
    assert prompt_digests(toks, 4, namespace="ta") == [
        prefix_digest(toks[:4], "ta"), prefix_digest(toks[:8], "ta"),
        da,
    ]
    kv = PagedKVCache(16, 4)
    kv.allocate("a", 12)
    kv.register_prefix("a", toks, namespace="ta")
    assert kv.match_prefix(toks, namespace="ta")
    assert kv.match_prefix(toks, namespace="tb") == []
    assert kv.match_prefix(toks) == []           # default namespace too
    assert da in kv.prefix_digests()


def test_request_prefix_namespace_follows_tenant_unless_shared():
    """A request's prefix pages index under its tenant by default;
    ``shared_prefix`` opts into the unsalted shared namespace (the
    common-system-prompt case), and untenanted requests land there
    already."""
    r = Request(request_id="r", prompt=[1], max_new_tokens=1,
                tenant="ta")
    assert r.prefix_namespace == "ta"
    s = Request(request_id="s", prompt=[1], max_new_tokens=1,
                tenant="ta", shared_prefix=True)
    assert s.prefix_namespace is None
    t = Request(request_id="t", prompt=[1], max_new_tokens=1)
    assert t.prefix_namespace is None


def test_scheduler_tenant_prefix_isolation_and_shared_optin(lm,
                                                            lm_params):
    """Two tenants submitting the SAME prompt must not share prefix
    pages (zero cross-tenant prefix hits); with ``shared_prefix`` both
    land in the shared namespace and the second reuses the first's
    pages.  Streams are bit-identical throughout — isolation changes
    page accounting, never tokens."""
    from chainermn_tpu.serving import ContinuousBatchingScheduler

    prompt = [int(t) for t in
              np.random.default_rng(3).integers(0, VOCAB, size=9)]
    want = oracle_streams(lm, lm_params, [prompt], 5)[0]

    def run(shared):
        eng = make_engine(lm, lm_params)
        sched = ContinuousBatchingScheduler(eng)
        # sequential, so the second tenant's prompt arrives AFTER the
        # first's prefix pages are registered — a hit iff shareable
        for i, ten in enumerate(("ta", "tb")):
            sched.add_request(Request(
                request_id=f"r{i}", prompt=list(prompt),
                max_new_tokens=5, tenant=ten, shared_prefix=shared))
            while sched.has_work:
                sched.step()
        assert [r.generated for r in sched.results().values()] \
            == [want, want]
        return eng._tokens_prefix_cached

    assert run(shared=False) == 0          # isolated: no reuse
    assert run(shared=True) > 0            # opted in: pages shared


# ---------------------------------------------------------------------------
# Shard groups: plan_groups, lockstep mirroring, pipelined decode
# ---------------------------------------------------------------------------


def test_plan_groups_partitions_ranks_into_leader_led_runs():
    from chainermn_tpu.serving.cluster import plan_groups

    groups = plan_groups(5, group_size=2)
    assert [g.leader for g in groups] == [1, 3]
    assert [g.followers for g in groups] == [(2,), (4,)]
    assert all(g.group_size == 2 and g.pp_stages == 1 for g in groups)
    assert groups[0].ranks == (1, 2) and groups[0].n_shards == 2

    # tp x pp: shard count is the product
    tp_pp = plan_groups(5, group_size=2, pp_stages=2)
    assert len(tp_pp) == 1 and tp_pp[0].ranks == (1, 2, 3, 4)
    assert tp_pp[0].n_shards == 4 and tp_pp[0].pp_stages == 2

    # K=1 degenerates to today's one-process replicas
    solo = plan_groups(4)
    assert [g.leader for g in solo] == [1, 2, 3]
    assert all(g.followers == () for g in solo)

    with pytest.raises(ValueError):
        plan_groups(4, group_size=2)     # 3 ranks don't split into 2s
    with pytest.raises(ValueError):
        plan_groups(2, group_size=2)     # not even one full group


def test_engine_mirror_replay_lockstep_parity(lm, lm_params):
    """The shard-group invariant, single-process: a follower that only
    replays the leader's mirrored device steps (prefill / decode /
    chunk / cow / defrag) over its own identically-seeded params ends
    the workload with a BIT-IDENTICAL KV cache — no scheduler, no
    sampler, no block tables of its own.  Mixed greedy + sampled
    traffic with a shared prefix, so the replay covers the chunk
    (suffix prefill) and CoW (rewind) ops, not just the easy two."""
    from chainermn_tpu.serving import ContinuousBatchingScheduler

    leader = make_engine(lm, lm_params)
    follower = make_engine(lm, lm_params)
    ops = []
    leader.mirror_sink = lambda op, payload: ops.append((op, payload))

    rng = np.random.default_rng(11)
    shared = [int(t) for t in rng.integers(0, VOCAB, size=8)]
    sched = ContinuousBatchingScheduler(leader)
    for i in range(3):
        # r2's prompt IS the shared prefix: fully cached, so the
        # scheduler takes the CoW-rewind path ("cow" coverage).
        tail = ([int(t) for t in rng.integers(0, VOCAB, size=3 + i)]
                if i < 2 else [])
        sched.add_request(Request(
            request_id=f"r{i}", prompt=shared + tail, max_new_tokens=6,
            sampling=(SamplingParams() if i % 2 == 0 else
                      SamplingParams(temperature=0.9, top_k=8,
                                     seed=100 + i)),
        ))
        while sched.has_work:
            sched.step()
    # Deterministic fragmentation: compact first, then leave a hole
    # below a live allocation so this defragment MUST move pages.
    leader.defragment()
    leader.kv.allocate("x", 8)
    leader.kv.allocate("y", 8)
    leader.kv.free("x")
    assert leader.defragment() > 0

    assert {op for op, _ in ops} >= {"prefill", "decode", "chunk",
                                     "cow", "defrag"}
    for op, payload in ops:
        follower.apply_step(op, payload)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        leader._cache, follower._cache,
    )
    with pytest.raises(ValueError):
        follower.apply_step("nonsense", ())


def test_pp_microbatched_decode_streams_bit_exact(lm, lm_params):
    """Splitting the decode batch into pipeline microbatches must not
    change a single token: per-sequence attention + counter-based
    sampling make each row's result independent of batch composition,
    so the contiguous-span split is bit-exact by construction.  This is
    the invariant that lets pp_stages be a pure throughput knob."""
    from chainermn_tpu.serving import ContinuousBatchingScheduler

    prompts = prompts_for(4, rng_seed=23)
    want = oracle_streams(lm, lm_params, prompts, 6)

    def run(pp):
        eng = make_engine(lm, lm_params)
        eng.pp_stages = pp
        sched = ContinuousBatchingScheduler(eng)
        for i, p in enumerate(prompts):
            sched.add_request(Request(
                request_id=i, prompt=list(p), max_new_tokens=6))
        while sched.has_work:
            sched.step()
        res = sched.results()
        return [res[i].generated for i in range(len(prompts))]

    assert run(1) == want
    assert run(2) == want
    assert run(3) == want


def test_prefix_gossip_versioned_anti_entropy():
    """Snapshots apply strictly-newer only: duplicates and reordered
    deliveries are no-ops, so load-beat gossip is idempotent."""
    from chainermn_tpu.serving.cluster import PrefixGossip

    g = PrefixGossip()
    assert g.observe("B", 2, (10, 20, 30))
    assert not g.observe("B", 2, (10, 20, 30))       # dup
    assert not g.observe("B", 1, (99,))              # stale reorder
    assert g.hit_pages([10, 20, 30], "B") == 3
    assert g.hit_pages([10, 99, 30], "B") == 1       # leading run only
    assert g.hit_pages([99, 20], "B") == 0
    assert g.observe("B", 5, (10,))                  # newer wins
    assert g.hit_pages([10, 20], "B") == 1
    assert g.best([10]) == ("B", 1)
    g.forget("B")
    assert g.hit_pages([10], "B") == 0 and g.replicas() == []


def test_kv_index_version_bumps_on_mutation(lm, lm_params):
    """Every prefix-index mutation bumps the anti-entropy stamp, so a
    receiver can order snapshots without clocks."""
    engine = make_engine(lm, lm_params)
    v0 = engine.kv.index_version
    engine.generate(prompts_for(1, rng_seed=2, lo=8, hi=9)[0], 2)
    kv = engine.kv
    kv.allocate("w", 8)
    kv.register_prefix("w", list(range(8)))
    assert kv.index_version > v0
    v1 = kv.index_version
    kv.free("w")
    kv.drop_prefix_cache()
    assert kv.index_version > v1


def test_router_gossip_routes_to_warm_replica(lm, lm_params):
    """Same-template traffic converges on the replica already holding
    the template's pages — scored from the gossiped digest view, not
    just the in-process index probe."""
    template = prompts_for(1, rng_seed=41, lo=12, hi=13)[0]  # 3 pages
    reps, router = _mk_cluster(lm, lm_params, n=3)
    h0 = router.submit(list(template), 4)
    router.run_until_idle()
    warm = h0.replica_id
    router.step()                        # anti-entropy load beat
    dig = prompt_digests(template, 4)
    assert router.gossip.hit_pages(dig, warm) >= 3
    tails = prompts_for(3, rng_seed=43, lo=4, hi=8)
    handles = [router.submit(template + t, 4) for t in tails]
    router.run_until_idle()
    want = oracle_streams(lm, lm_params,
                          [template + t for t in tails], 4)
    for h, w in zip(handles, want):
        assert h.status == "finished" and h.tokens == w
        assert h.replica_id == warm      # prefix affinity held
    for r in reps:
        r.engine.kv.assert_consistent()


def test_stale_gossip_falls_back_to_local_prefill(lm, lm_params):
    """A phantom remote hit (gossip lags the holder dropping its
    cache) may still steer routing — but the chosen replica's
    admission re-probes its OWN index, so the request degrades to a
    full local prefill with the stream bit-exact, never corrupt."""
    template = prompts_for(1, rng_seed=41, lo=12, hi=13)[0]
    reps, router = _mk_cluster(lm, lm_params, n=2)
    h0 = router.submit(list(template), 4)
    router.run_until_idle()
    warm = h0.replica_id
    router.step()                        # gossip now advertises warm
    # the holder loses its cache; the router's view goes stale
    reps[warm].engine.kv.drop_prefix_cache()
    prompt = template + prompts_for(1, rng_seed=47, lo=4, hi=5)[0]
    h = router.submit(list(prompt), 4)
    router.run_until_idle()
    want = oracle_streams(lm, lm_params, [prompt], 4)[0]
    assert h.status == "finished" and h.tokens == want
    assert h.replica_id == warm          # routed by the stale view
    sched = reps[warm].scheduler
    assert sched._prefix_hit_tokens == 0  # phantom: local re-probe missed
    reps[warm].engine.kv.assert_consistent()
    # the next beat re-syncs the view to the replica's CURRENT index
    # (which now holds the just-served prompt — template included —
    # re-registered by its full local prefill)
    router.step()
    kv = reps[warm].engine.kv
    assert router.gossip.version(warm) == kv.index_version
    assert router.gossip.hit_pages(prompt_digests(template, 4), warm) \
        == len(kv.match_prefix(template)) == 3


def test_replica_load_gossip_fields_roundtrip(lm, lm_params):
    """ReplicaLoad carries the digest snapshot over the wire dict
    format unchanged, and peers predating the fields still parse."""
    from chainermn_tpu.serving.cluster import ReplicaLoad

    rep = Replica(0, make_engine(lm, lm_params))
    rep.frontend.submit(prompts_for(1, rng_seed=41, lo=12, hi=13)[0], 2)
    while rep.scheduler.has_work:
        rep.step()
    ld = rep.load()
    assert ld.block_size == 4 and ld.prefix_version > 0
    assert len(ld.prefix_digests) > 0
    assert ReplicaLoad.from_dict(ld.as_dict()) == ld
    # wire compat: an old peer's dict without the gossip fields
    old = {k: v for k, v in ld.as_dict().items()
           if k not in ("block_size", "prefix_version",
                        "prefix_digests")}
    ld_old = ReplicaLoad.from_dict(old)
    assert ld_old.block_size == 0 and ld_old.prefix_digests == ()


def test_replica_load_max_bucket_roundtrip(lm, lm_params):
    """The warm-ladder watermark rides the load beat: after a replica
    serves a prompt past its seed ladder, its gossiped ``max_bucket``
    covers the full context, survives the wire dict roundtrip, and an
    old peer's dict without the field still parses (cold: 0)."""
    from chainermn_tpu.serving.cluster import ReplicaLoad

    rep = Replica(0, make_engine(lm, lm_params, prefill_buckets=(8,)))
    prompt = prompts_for(1, rng_seed=71, lo=20, hi=21)[0]
    rep.frontend.submit(list(prompt), 2)
    while rep.scheduler.has_work:
        rep.step()
    ld = rep.load()
    assert ld.max_bucket >= len(prompt)
    assert ReplicaLoad.from_dict(ld.as_dict()) == ld
    old = {k: v for k, v in ld.as_dict().items() if k != "max_bucket"}
    assert ReplicaLoad.from_dict(old).max_bucket == 0


def test_router_warm_ladder_routes_long_prompts(lm, lm_params):
    """A prompt past the seed bucket ladder prefers the replica whose
    ladder already grew to cover it — even with ZERO shared pages: the
    warm replica serves it without a growth recompile.  The prefix
    cache is wiped first so only the ladder watermark can steer."""
    reps = [Replica(i, make_engine(lm, lm_params, prefill_buckets=(8,)))
            for i in range(2)]
    router = ReplicaRouter(reps)
    long0 = prompts_for(1, rng_seed=73, lo=20, hi=21)[0]
    reps[0].frontend.submit(list(long0), 2)  # grow replica 0's ladder
    while reps[0].scheduler.has_work:
        reps[0].step()
    assert reps[0].engine.max_bucket >= len(long0)
    # no shared pages can help the score: wipe the cache, keep the
    # ladder warm (compiled buckets are engine state, not kv state)
    reps[0].engine.kv.drop_prefix_cache()
    router.step()                        # load beat re-syncs the view
    prompt = prompts_for(1, rng_seed=79, lo=12, hi=13)[0]
    assert len(prompt) > 8               # past replica 1's cold ladder
    h = router.submit(list(prompt), 4)
    router.run_until_idle()
    want = oracle_streams(lm, lm_params, [prompt], 4)[0]
    assert h.status == "finished" and h.tokens == want
    # otherwise-identical scores tie-break to replica 1; only the
    # warm-ladder bonus can have pulled the placement to replica 0
    assert h.replica_id == 0
    for r in reps:
        r.engine.kv.assert_consistent()


# ---------------------------------------------------------------------------
# Health: heartbeats, scale signals, gauges
# ---------------------------------------------------------------------------


def test_heartbeat_monitor_detects_and_revives():
    clock = [0.0]
    mon = HeartbeatMonitor([0, 1], miss_after_s=2.0,
                           clock=lambda: clock[0])
    mon.beat(0)
    mon.beat(1)
    clock[0] = 1.0
    assert mon.check() == []
    clock[0] = 2.5
    mon.beat(1)
    assert mon.check() == [0]       # newly dead, exactly once
    assert mon.check() == []
    assert not mon.alive(0) and mon.alive(1)
    mon.beat(0)                     # replacement process beats again
    assert mon.alive(0)
    clock[0] = 3.0
    assert mon.check() == []


def test_scale_signals_pressure_and_drain(lm, lm_params):
    reps, router = _mk_cluster(lm, lm_params, n=2)
    sig = scale_signals(router.loads())
    assert sig["replicas_alive"] == 2
    assert sig["scale_up"] is False
    # idle twin fleet: one replica is a drain candidate
    assert sig["drain_candidate"] is not None
    # saturate the queues → scale-up signal, no drain candidate
    for h in range(20):
        try:
            router.submit(prompts_for(1)[0], 4)
        except QueueFull:
            break
    sig = scale_signals(router.loads(), queue_pressure_frac=0.1)
    assert sig["queued"] > 0
    assert sig["drain_candidate"] is None
    router.run_until_idle()


def test_replica_gauges_and_prometheus_labels(lm, lm_params):
    from chainermn_tpu.observability import Reporter
    from chainermn_tpu.tools.obs import to_prometheus

    rep = Reporter()
    replica = Replica("r0", make_engine(lm, lm_params), reporter=rep)
    h = replica.frontend.submit(prompts_for(1)[0], 4)
    while not h.done:
        replica.step()
    g = rep.summary()["gauges"]
    assert g["serving/running/replica/r0"]["value"] == 0
    assert g["serving/free_blocks/replica/r0"]["value"] == 64
    # bare names (single-engine serving) stay unsuffixed
    assert "serving/running" not in g

    summary = {"gauges": {
        "serving/running/replica/r0": {"sum": 2.0, "max": 2.0},
        "serving/running": {"sum": 1.0, "max": 1.0},
    }}
    prom = to_prometheus(summary)
    assert ('chainermn_tpu_gauge{name="serving/running",'
            'replica="r0"} 2' in prom)
    assert 'chainermn_tpu_gauge{name="serving/running"} 1' in prom


# ---------------------------------------------------------------------------
# CLI + threaded soak
# ---------------------------------------------------------------------------


def test_serve_cli_local_verify_smoke():
    from conftest import subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "chainermn_tpu.tools.serve",
         "--replicas", "2", "--verify", "--requests", "4",
         "--new-tokens", "6", "--prompt-len", "8",
         "--vocab", "32", "--d-model", "16", "--d-ff", "32",
         "--max-len", "64", "--block-size", "4", "--n-blocks", "32"],
        capture_output=True, text=True, timeout=420,
        env=subprocess_env(n_devices=1), cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["parity"] == "ok"
    assert out["statuses"] == {"finished": 4}
    assert out["tokens"] == 24


def test_bench_serve_cluster_disagg_proof_smoke():
    from conftest import subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--serve",
         "--serve-replicas", "2",
         "--lm-vocab", "32", "--lm-d-model", "16", "--lm-heads", "2",
         "--lm-d-ff", "32", "--lm-layers", "1",
         "--serve-batch-sizes", "2", "--serve-requests", "3",
         "--serve-prompt-len", "6", "--serve-new-tokens", "4",
         "--serve-block-size", "4", "--serve-blocks", "64",
         "--serve-max-len", "64", "--serve-queue", "8"],
        capture_output=True, text=True, timeout=420,
        env=subprocess_env(n_devices=1), cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    # the single-engine report shape is intact...
    assert out["unit"] == "tokens/sec" and out["value"] > 0
    # ...and the cluster section carries the disaggregation evidence
    cl = out["cluster"]
    assert cl["replicas"] == 2
    assert cl["routed"]["finished"] == cl["routed"]["requests"]
    proof = cl["disagg_proof"]
    assert proof["single_replica_mixed"]["finished"] == 4
    assert proof["disaggregated"]["finished"] == 4
    assert proof["long_prompt_len"] > 6


def test_serving_cluster_soak_threaded_failover(lm, lm_params):
    """Soak (auto-marked slow): threaded replicas, concurrent
    submission, one replica killed mid-stream — every stream bit-exact
    vs the sequential oracle, survivor invariants intact."""
    prompts = prompts_for(10, rng_seed=21, lo=4, hi=12)
    # half the traffic shares a 2-page prefix so the kill lands with
    # refcounted/registered pages live in every pool
    rng = np.random.default_rng(37)
    shared = [int(t) for t in rng.integers(0, VOCAB, size=8)]
    prompts = [shared + p if i % 2 == 0 else p
               for i, p in enumerate(prompts)]
    want = oracle_streams(lm, lm_params, prompts, 8)
    reps = [Replica(i, make_engine(lm, lm_params), max_queue=16,
                    spec_tokens=2)
            for i in range(3)]
    router = ReplicaRouter(
        reps, health=HeartbeatMonitor([0, 1, 2], miss_after_s=1e9),
    )
    with ThreadedClusterDriver(router) as drv:
        handles = [router.submit(p, 8, timeout_s=120.0)
                   for p in prompts]
        # let some tokens commit, then kill whichever replica owns
        # the first unfinished handle
        while sum(len(h.tokens) for h in handles) < 5:
            router.step(drive_replicas=False)
        victim = next(
            (h.replica_id for h in handles
             if not h.done and h.replica_id is not None), 0,
        )
        router.fail_replica(victim, "soak kill")
        drv.run_until_idle(timeout_s=240.0)
    for h, w in zip(handles, want):
        assert h.status == "finished", (h.request_id, h.status, h.error)
        assert h.tokens == w
    for r in reps:
        if r.replica_id != victim:
            r.engine.kv.assert_consistent()


# ---------------------------------------------------------------------------
# Fleet metrics plane: beat-carried snapshots, idempotent merge,
# dead-replica series hygiene, per-tenant accounting through the view
# ---------------------------------------------------------------------------


def test_metrics_gossip_idempotent_under_dup_and_reorder():
    """Replaying the beat stream in any order, with duplicates, folds to
    the same fleet view — the strictly-newer version check makes the
    merge idempotent exactly like the prefix index."""
    import random

    from chainermn_tpu.observability.reporter import Reporter
    from chainermn_tpu.serving.cluster import MetricsGossip

    def snap(steps, tokens):
        r = Reporter()
        r.count("serving/steps", steps)
        r.count("serving/tokens", tokens)
        r.gauge(f"serving/running/replica/{steps}", steps)
        return r.summary()

    beats = [(1, 1, snap(1, 10)), (1, 2, snap(2, 25)),
             (2, 1, snap(3, 7)), (2, 2, snap(5, 9))]
    g = MetricsGossip()
    for rid, v, s in beats:
        assert g.observe(rid, v, s)
    want = g.fleet_view()
    assert want["counters"]["serving/steps"] == 2 + 5
    assert want["counters"]["serving/tokens"] == 25 + 9

    rng = random.Random(7)
    for _ in range(5):
        replay = beats * 3
        rng.shuffle(replay)
        g2 = MetricsGossip()
        for rid, v, s in replay:
            g2.observe(rid, v, s)
        assert g2.fleet_view() == want
        assert g2.version(1) == 2 and g2.version(2) == 2

    # wire compat: None summaries and stale versions are no-ops
    assert not g.observe(1, 5, None)
    assert not g.observe(1, 1, snap(99, 99))
    assert g.fleet_view() == want
    # forget drops the replica's whole contribution from the next view
    g.forget(2)
    assert g.replicas() == [1]
    assert g.fleet_view()["counters"]["serving/steps"] == 2
    assert g.latest(2) is None and g.version(2) is None


def test_fleet_view_tenants_and_dead_replica_series_drop(lm, lm_params):
    """End-to-end fleet plane, in process: each replica owns a registry
    gossiped on its load beats, the router's fleet_view merges them with
    its own reporter (per-tenant counters included), and failing a
    replica drops its per-replica series from the very next view."""
    from chainermn_tpu.observability.reporter import Reporter

    router_rep = Reporter()
    mreps = {i: Reporter() for i in range(2)}
    reps = [
        Replica(i, make_engine(lm, lm_params), role="both",
                reporter=mreps[i], metrics_reporter=mreps[i],
                max_queue=8)
        for i in range(2)
    ]
    router = ReplicaRouter(
        reps, reporter=router_rep,
        health=HeartbeatMonitor([0, 1], miss_after_s=1e9),
    )
    prompts = prompts_for(4, rng_seed=19)
    handles = [router.submit(p, 6, tenant=f"t{i % 2}")
               for i, p in enumerate(prompts)]
    router.run_until_idle()
    assert all(h.status == "finished" for h in handles)

    view = router.fleet_view()
    # one scrape covers the fleet: per-tenant token accounting is exact
    produced = sum(len(h.tokens) for h in handles)
    assert (view["counters"]["tenant/t0/tokens_out"]
            + view["counters"]["tenant/t1/tokens_out"]) == produced
    assert (view["counters"]["tenant/t0/tokens_in"]
            + view["counters"]["tenant/t1/tokens_in"]
            ) == sum(len(p) for p in prompts)
    assert view["counters"]["tenant/t0/admit"] == 2
    # per-tenant KV residency gauges rode the beats in
    assert view["gauges"]["tenant/t0/kv_page_seconds"]["value"] > 0
    # per-replica series from BOTH replicas are visible in the one view
    for rid in (0, 1):
        assert any(k.endswith(f"/replica/{rid}") for k in view["gauges"])

    # kill replica 0: snapshot AND router-side per-replica series drop
    # from the very next fleet_view — no beat needed, no stale series
    router.fail_replica(0, "test kill")
    view2 = router.fleet_view()
    for table in ("gauges", "counters", "histograms"):
        stale = [k for k in view2.get(table, {})
                 if k.endswith("/replica/0") or "/replica/0/" in k]
        assert not stale, (table, stale)
    assert 0 not in router.metrics.replicas()
    # the survivor's series are untouched
    assert any(k.endswith("/replica/1") for k in view2["gauges"])
    reps[1].engine.kv.assert_consistent()


def test_retire_replica_forgets_metrics_snapshot(lm, lm_params):
    """Planned scale-down hygiene matches the failure path: retiring a
    drained replica removes its gossiped snapshot and per-replica
    series from the fleet view."""
    from chainermn_tpu.observability.reporter import Reporter

    router_rep = Reporter()
    mreps = {i: Reporter() for i in range(2)}
    reps = [
        Replica(i, make_engine(lm, lm_params), role="both",
                reporter=mreps[i], metrics_reporter=mreps[i],
                max_queue=8)
        for i in range(2)
    ]
    router = ReplicaRouter(reps, reporter=router_rep)
    # enough concurrent work that BOTH replicas serve some of it, so
    # the survivor's snapshot carries tenant counters after the retire
    handles = [router.submit(p, 4, tenant="acme")
               for p in prompts_for(6, rng_seed=23)]
    router.run_until_idle()
    assert all(h.status == "finished" for h in handles)
    assert {h.replica_id for h in handles} == {0, 1}
    assert 1 in router.metrics.replicas()
    router.drain(1)
    router.migrate_out(1)
    router.run_until_idle()
    assert router.retire_replica(1)
    assert 1 not in router.metrics.replicas()
    view = router.fleet_view()
    assert not any(
        k.endswith("/replica/1") or "/replica/1/" in k
        for table in ("gauges", "counters", "histograms")
        for k in view.get(table, {})
    )
    # tenant counters from the SURVIVOR keep accumulating in the view
    assert view["counters"]["tenant/acme/tokens_out"] > 0
