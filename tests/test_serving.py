"""Serving subsystem: paged KV cache, engine, scheduler, frontend.

The contract under test, in rough order of importance:

1. **Bit-exact batching** — a request's token stream is identical
   whether it runs alone (``engine.generate``), shares continuous-
   batched iterations, or is preempted and recomputed mid-flight.
   Token-id comparisons: greedy argmax over fp32 logits makes them an
   exact-equality surface.
2. **Page conservation** — no allocation pattern (including eviction
   churn and defragmentation) leaks or aliases a page.
3. **Bounded recompiles** — compiled step count tracks the bucket
   ladder, not the request count.
4. **Policy behavior** — FCFS admission, latest-first preemption,
   queue backpressure, deadline expiry (fake clock: no sleeps).
5. **Collective-free decode** — the jitted decode step's HLO census is
   pinned empty in ``tests/golden/serving_decode_census.json``
   (regen: ``python tests/test_serving.py --regen``).

All CPU; the module-scope LM keeps the suite's jit count low.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chainermn_tpu.serving import (
    ContinuousBatchingScheduler,
    EngineConfig,
    InferenceEngine,
    OutOfBlocks,
    PagedKVCache,
    QueueFull,
    Request,
    SamplingParams,
    ServeFrontend,
)

CENSUS_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "serving_decode_census.json",
)
SP_CENSUS_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "serving_sp_prefill_census.json",
)
TP_CENSUS_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "golden", "serving_tp_decode_census.json",
)

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    from chainermn_tpu.models.transformer import TransformerLM

    return TransformerLM(vocab=VOCAB, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, max_len=64)


@pytest.fixture(scope="module")
def lm_params(lm):
    return lm.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))


@pytest.fixture(scope="module")
def oracle(lm, lm_params):
    """Naive full-recompute greedy decode on the plain dense model — the
    reference every cached-KV path must match bit-exactly."""

    def run(prompt, n):
        toks = list(map(int, prompt))
        out = []
        for _ in range(n):
            logits = lm.apply(lm_params, jnp.asarray([toks], jnp.int32))
            out.append(int(np.argmax(
                np.asarray(logits[0, -1], np.float32)
            )))
            toks.append(out[-1])
        return out

    return run


def make_engine(lm, lm_params, **over):
    cfg = dict(block_size=4, n_blocks=64, max_len=64, max_batch=4)
    cfg.update(over)
    return InferenceEngine(lm, lm_params, EngineConfig(**cfg))


def prompts_for(n, rng_seed=7, lo=3, hi=13):
    rng = np.random.default_rng(rng_seed)
    return [
        [int(t) for t in rng.integers(0, VOCAB, size=int(l))]
        for l in rng.integers(lo, hi, size=n)
    ]


# ---------------------------------------------------------------------------
# PagedKVCache: accounting invariants
# ---------------------------------------------------------------------------
def test_kv_cache_alloc_free_conservation():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    t = kv.allocate("a", 9)          # 3 pages
    assert t == [0, 1, 2] and kv.used_blocks == 3
    kv.assert_consistent()
    kv.allocate("b", 4)              # 1 page
    kv.assert_consistent()
    assert kv.free("a") == 3
    kv.assert_consistent()
    assert kv.used_blocks == 1 and "a" not in kv and "b" in kv
    with pytest.raises(KeyError):
        kv.free("a")
    with pytest.raises(ValueError):
        kv.allocate("b", 1)          # double-allocate
    kv.free("b")
    assert kv.used_blocks == 0 and kv.stats().utilization == 0.0


def test_kv_cache_extend_and_out_of_blocks():
    kv = PagedKVCache(n_blocks=4, block_size=4)
    kv.allocate("a", 4)
    assert kv.extend("a", 5) == [1]      # crosses a page boundary
    assert kv.extend("a", 8) == []       # within the second page
    kv.assert_consistent()
    kv.allocate("b", 8)
    with pytest.raises(OutOfBlocks):
        kv.extend("a", 9)
    with pytest.raises(OutOfBlocks):
        kv.allocate("c", 1)
    kv.assert_consistent()               # failed ops must not leak
    assert not kv.can_allocate(1)
    kv.free("b")
    assert kv.can_allocate(8) and not kv.can_allocate(8, reserve=1)


def test_kv_cache_padded_table_uses_oob_sentinel():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    kv.allocate("a", 5)
    t = kv.padded_table("a", 4)
    assert t.dtype == np.int32
    assert list(t) == [0, 1, kv.invalid, kv.invalid]
    assert kv.invalid == 8               # OOB-high, never negative
    with pytest.raises(ValueError):
        kv.padded_table("a", 1)


def test_kv_prefix_share_refcounts_and_cow_split():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    toks = list(range(10))                   # 2 full pages + 2 tokens
    kv.allocate("a", 10)
    assert kv.match_prefix(toks) == []       # nothing registered yet
    assert kv.register_prefix("a", toks) == 2
    hit = kv.match_prefix(toks)
    assert hit == kv.block_table("a")[:2]
    # second sequence shares the head; only the suffix draws pages
    before = kv.free_blocks
    kv.allocate("b", 10, prefix_pages=hit)
    assert kv.block_table("b")[:2] == hit
    assert before - kv.free_blocks == 1      # 1 fresh page, not 3
    assert kv.refcount(hit[0]) == 2
    kv.assert_consistent()
    # first partial-page write into a shared page → CoW split
    split = kv.make_writable("b", 4)         # position in shared page 2
    assert split is not None
    old, new = split
    assert old == hit[1] and kv.block_table("b")[1] == new
    assert kv.refcount(old) == 1 and kv.refcount(new) == 1
    # a's table still points at the original; the index is untouched
    assert kv.block_table("a")[1] == old
    assert kv.match_prefix(toks) == hit
    # private unregistered pages never split
    assert kv.make_writable("b", 9) is None
    kv.assert_consistent()


def test_kv_evict_one_of_two_sharers():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    toks = list(range(8))
    kv.allocate("a", 8)
    kv.register_prefix("a", toks)
    shared = kv.match_prefix(toks)
    kv.allocate("b", 8, prefix_pages=shared)
    # evict (preempt/free) one sharer: pages survive with refcount 1
    kv.free("a")
    kv.assert_consistent()
    assert [kv.refcount(p) for p in shared] == [1, 1]
    assert kv.match_prefix(toks) == shared   # still shareable
    # evict the second: refcount-0 registered pages PARK, not free
    kv.free("b")
    kv.assert_consistent()
    assert kv.cached_blocks == 2
    assert kv.match_prefix(toks) == shared
    # resurrection from the cached pool costs nothing
    kv.allocate("c", 8, prefix_pages=kv.match_prefix(toks))
    assert kv.cached_blocks == 0 and kv.block_table("c") == shared
    kv.assert_consistent()


def test_kv_cached_pool_lru_eviction_under_pressure():
    kv = PagedKVCache(n_blocks=4, block_size=4)
    kv.allocate("a", 8)
    kv.register_prefix("a", list(range(8)))
    kv.free("a")                             # both pages parked
    assert kv.cached_blocks == 2
    assert kv.free_blocks == 4               # reclaimable counts cached
    # pool pressure evicts the OLDEST cached page and unregisters it
    kv.allocate("b", 12)                     # needs 3: 2 free + 1 cached
    kv.assert_consistent()
    assert kv.cached_blocks == 1
    assert len(kv.match_prefix(list(range(8)))) <= 1
    with pytest.raises(OutOfBlocks):
        kv.allocate("c", 8)                  # 1 cached + 0 free < 2
    kv.assert_consistent()


def test_kv_defragment_while_shared():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    toks = list(range(8))
    kv.allocate("a", 8)
    kv.register_prefix("a", toks)
    kv.allocate("hole", 8)
    kv.allocate("b", 10, prefix_pages=kv.match_prefix(toks))
    kv.free("hole")                          # holes mid-pool
    shared_before = kv.match_prefix(toks)
    perm = kv.defragment()
    kv.assert_consistent()                   # conservation incl. refcounts
    assert perm is not None
    # both sharers' tables moved TOGETHER and the index followed
    shared_after = kv.match_prefix(toks)
    assert kv.block_table("a")[:2] == shared_after
    assert kv.block_table("b")[:2] == shared_after
    assert [kv.refcount(p) for p in shared_after] == [2, 2]
    # permutation semantics: new slot i holds old page perm[i]
    assert [perm[p] for p in shared_after] == shared_before
    # cached (refcount-0) pages survive defrag too
    kv.free("a")
    kv.free("b")
    assert kv.cached_blocks == 2
    assert kv.defragment() is None or kv.match_prefix(toks)
    kv.assert_consistent()


def test_kv_cache_defragment_permutation_semantics():
    kv = PagedKVCache(n_blocks=8, block_size=4)
    kv.allocate("a", 8)
    kv.allocate("b", 8)
    kv.free("a")                          # holes at pages 0,1
    pages = np.arange(8)                  # fake device pages: id content
    old_table = kv.block_table("b")
    perm = kv.defragment()
    kv.assert_consistent()
    new_pages = pages[perm]               # engine: take(pages, perm, 0)
    # b's data moved with its table: content at the new slots is the old
    # page ids it occupied before.
    assert [new_pages[i] for i in kv.block_table("b")] == old_table
    assert kv.block_table("b") == [0, 1]  # dense prefix
    # already compact: no device copy, free list reseeded dense
    assert kv.defragment() is None
    assert kv.allocate("c", 4) == [2]


# ---------------------------------------------------------------------------
# Engine: cached-KV decode parity, buckets, defrag
# ---------------------------------------------------------------------------
def test_engine_greedy_matches_full_recompute_oracle(lm, lm_params,
                                                     oracle):
    engine = make_engine(lm, lm_params)
    for prompt in prompts_for(4):
        assert engine.generate(prompt, 6) == oracle(prompt, 6)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0    # generate() frees its sequence


def test_engine_recompile_count_tracks_buckets(lm, lm_params):
    engine = make_engine(lm, lm_params)
    lengths = [3, 5, 9, 12]              # table-width buckets 1, 2, 4, 4
    rng = np.random.default_rng(0)
    for L in lengths:
        engine.generate([int(t) for t in rng.integers(0, VOCAB, L)], 3)
    st1 = engine.stats()
    # compiles track buckets touched, never the request count
    assert 0 < st1["prefill_compiles"] <= 3, st1
    # the same length profile again (fresh tokens): ZERO new compiles
    for L in lengths * 2:
        engine.generate([int(t) for t in rng.integers(0, VOCAB, L)], 3)
    st2 = engine.stats()
    assert st2["prefill_compiles"] == st1["prefill_compiles"], (st1, st2)
    assert st2["decode_compiles"] == st1["decode_compiles"], (st1, st2)
    # a much longer prompt lands in untouched buckets: compiles grow
    engine.generate(list(range(30)), 3)
    assert engine.stats()["prefill_compiles"] > st2["prefill_compiles"]
    st3 = engine.stats()
    if "decode_jit_cache_size" in st3:   # cross-check jit's own view
        assert st3["decode_jit_cache_size"] == st3["decode_compiles"]


def test_engine_defragment_mid_stream_keeps_numerics(lm, lm_params,
                                                     oracle):
    engine = make_engine(lm, lm_params)
    prompt = prompts_for(1)[0]
    want = oracle(prompt, 5)
    sid = "s"
    engine.kv.allocate(sid, len(prompt))
    logits = engine.prefill(prompt, sid)
    got, cur = [], len(prompt)
    for step in range(5):
        nxt = int(np.argmax(logits))
        got.append(nxt)
        if step == 4:
            break
        engine.kv.extend(sid, cur + 1)
        if step == 1:
            # Punch a hole below a live page so compaction has to MOVE
            # pages — including this sequence's — then decode again:
            # the stream must not notice.  ("lo"/"hi" take the next two
            # pages off the LIFO free list; freeing "lo" leaves "hi"
            # stranded above a hole.)
            engine.kv.allocate("lo", engine.kv.block_size)
            engine.kv.allocate("hi", engine.kv.block_size)
            engine.kv.free("lo")
            assert engine.defragment() > 0
            engine.kv.free("hi")
        logits = engine.decode([nxt], [sid], [cur])[0]
        cur += 1
    engine.kv.free(sid)
    assert got == want


def test_sampling_params_validation_and_determinism():
    with pytest.raises(ValueError):
        SamplingParams(temperature=-1.0)
    with pytest.raises(ValueError):
        SamplingParams(top_k=-1)
    logits = np.random.default_rng(0).normal(size=VOCAB).astype(
        np.float32
    )
    sp = SamplingParams(temperature=0.8, top_k=5, seed=3)
    draws = {InferenceEngine.sample(logits, sp, position=7)
             for _ in range(4)}
    assert len(draws) == 1               # counter-based: reproducible
    # top-k truncation: every draw over many positions is a top-k token
    topk = set(np.argsort(logits)[-5:])
    for pos in range(50):
        assert InferenceEngine.sample(logits, sp, pos) in topk
    # greedy ignores the RNG entirely
    g = SamplingParams()
    assert InferenceEngine.sample(logits, g, 0) == int(np.argmax(logits))


# ---------------------------------------------------------------------------
# Scheduler: continuous batching == sequential; preemption; fairness
# ---------------------------------------------------------------------------
def test_scheduler_batched_equals_sequential(lm, lm_params, oracle):
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine)
    prompts = prompts_for(6)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=p,
                                  max_new_tokens=6))
    res = sched.run_to_completion()
    for i, p in enumerate(prompts):
        assert res[i].state.value == "finished"
        assert res[i].generated == oracle(p, 6), f"request {i} diverged"
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_scheduler_preemption_recompute_is_bit_exact(lm, lm_params,
                                                     oracle):
    # Pool sized to force eviction: 4 requests want ~4 pages each but
    # only 10 exist.  Everyone must still finish with the exact
    # unpreempted stream.
    engine = make_engine(lm, lm_params, n_blocks=10)
    sched = ContinuousBatchingScheduler(engine, watermark_blocks=0)
    prompts = prompts_for(4, rng_seed=11)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=p,
                                  max_new_tokens=6))
    res = sched.run_to_completion()
    assert sum(r.preemptions for r in res.values()) > 0, (
        "scenario no longer triggers preemption; shrink the pool"
    )
    for i, p in enumerate(prompts):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == oracle(p, 6)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_scheduler_admission_is_fcfs(lm, lm_params):
    # max_batch 2: with 4 waiting requests, the first two admitted must
    # be the first two submitted, and a request is only admitted after
    # an earlier one retires.
    engine = make_engine(lm, lm_params, max_batch=2)
    sched = ContinuousBatchingScheduler(engine)
    order = []
    for i, p in enumerate(prompts_for(4, rng_seed=3)):
        req = Request(request_id=i, prompt=p, max_new_tokens=4)
        req.on_token = (
            lambda rid, tok: order.append(rid) if rid not in order
            else None
        )
        sched.add_request(req)
    sched.step()
    assert sorted(r.request_id for r in sched.running) == [0, 1]
    sched.run_to_completion()
    assert order == [0, 1, 2, 3]         # first token order = FCFS


def test_scheduler_rejects_impossible_requests(lm, lm_params):
    engine = make_engine(lm, lm_params, n_blocks=2)  # 8-token pool
    sched = ContinuousBatchingScheduler(engine)
    sched.add_request(Request(request_id=0, prompt=list(range(30)),
                              max_new_tokens=50))    # > max_len
    sched.add_request(Request(request_id=1, prompt=list(range(20)),
                              max_new_tokens=4))     # > pool
    sched.add_request(Request(request_id=2, prompt=[], max_new_tokens=4))
    res = sched.run_to_completion()
    assert res[0].state.value == "failed" and "max_len" in res[0].error
    assert res[1].state.value == "failed"
    assert res[2].state.value == "failed"
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_scheduler_tenant_drr_interleaves_backlogged_tenants(
        lm, lm_params):
    """A tenant that floods the queue first no longer monopolizes
    admission: with equal weights, two backlogged tenants alternate
    (FIFO preserved *within* each tenant), and clearing the weights
    reverts to the historical global FCFS exactly."""
    def run(weights):
        engine = make_engine(lm, lm_params, max_batch=1)
        sched = ContinuousBatchingScheduler(engine)
        sched.set_tenant_weights(weights)
        order = []
        for i in range(8):
            req = Request(request_id=i, prompt=[1 + i % 8, 2, 3],
                          max_new_tokens=4,
                          tenant="a" if i < 4 else "b")
            req.on_token = (
                lambda rid, tok: order.append(rid) if rid not in order
                else None
            )
            sched.add_request(req)
        sched.run_to_completion()
        return order

    # all of tenant a submitted before any of tenant b, equal costs
    assert run({"a": 1.0, "b": 1.0}) == [0, 4, 1, 5, 2, 6, 3, 7]
    assert run(None) == list(range(8))        # off-switch: strict FCFS


def test_scheduler_tenant_drr_weighted_shares_and_gauges(
        lm, lm_params):
    """Weights divide admission service: at 2:1 and equal costs, the
    first 9 serialized admissions split exactly 6/3, and the deficit
    counters ride the Reporter as serve/tenant_deficit/<id> gauges."""
    from chainermn_tpu.observability import Reporter

    rep = Reporter()
    engine = make_engine(lm, lm_params, max_batch=1)
    sched = ContinuousBatchingScheduler(engine, reporter=rep)
    sched.set_tenant_weights({"a": 2.0, "b": 1.0})
    order = []
    for i in range(24):
        req = Request(request_id=i, prompt=[1 + i % 8, 2, 3],
                      max_new_tokens=4,
                      tenant="a" if i % 2 == 0 else "b")
        req.on_token = (
            lambda rid, tok: order.append(rid) if rid not in order
            else None
        )
        sched.add_request(req)
    sched.run_to_completion()
    first9 = order[:9]
    by_tenant = {"a": 0, "b": 0}
    for rid in first9:
        by_tenant["a" if rid % 2 == 0 else "b"] += 1
    assert by_tenant == {"a": 6, "b": 3}
    # FIFO within each tenant throughout
    for parity in (0, 1):
        got = [rid for rid in order if rid % 2 == parity]
        assert got == sorted(got)
    gauges = rep.summary()["gauges"]
    assert any(k.startswith("serve/tenant_deficit/") for k in gauges)


def test_scheduler_publishes_gauges_and_counters(lm, lm_params):
    from chainermn_tpu.observability import Reporter

    rep = Reporter()
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine, reporter=rep)
    for i, p in enumerate(prompts_for(3)):
        sched.add_request(Request(request_id=i, prompt=p,
                                  max_new_tokens=4))
    sched.step()
    mid = rep.summary()["gauges"]
    assert mid["serving/running"]["value"] > 0
    assert mid["serving/cache_utilization"]["value"] > 0
    sched.run_to_completion()
    s = rep.summary()
    assert s["gauges"]["serving/running"]["value"] == 0   # last wins
    assert s["counters"]["serving/tokens"] == 12


def test_prefix_and_spec_gauges_flow_to_prometheus(lm, lm_params):
    """serve/prefix_hit_rate and serve/spec_accept_len reach the
    Reporter once their mechanisms fire, and render through the
    Prometheus exporter."""
    from chainermn_tpu.observability import Reporter
    from chainermn_tpu.tools.obs import to_prometheus

    rep = Reporter()
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine, reporter=rep,
                                        spec_tokens=3)
    # repetitive prompt → the n-gram speculator proposes drafts
    shared = [1, 2, 3, 4, 1, 2, 3, 4]        # two full pages
    sched.add_request(Request(request_id=0, prompt=list(shared),
                              max_new_tokens=4))
    sched.run_to_completion()
    # same prompt again AFTER its pages were registered → prefix hit
    sched.add_request(Request(request_id=1,
                              prompt=list(shared) + [5, 6],
                              max_new_tokens=4))
    sched.run_to_completion()
    g = rep.summary()["gauges"]
    assert g["serve/prefix_hit_rate"]["value"] > 0
    assert g["serve/spec_accept_len"]["value"] >= 1.0
    prom = to_prometheus(rep.summary())
    assert 'name="serve/prefix_hit_rate"' in prom
    assert 'name="serve/spec_accept_len"' in prom
    engine.kv.assert_consistent()


# ---------------------------------------------------------------------------
# Prefix cache + speculative decoding: the bit-exactness contract
# ---------------------------------------------------------------------------
def _shared_prefix_prompts():
    """Duplicate-prefix traffic: alternating prompts share an 8-token
    (2 full pages) head, one prompt IS exactly the shared head (the
    full-hit CoW-rewind path), the rest are fully random."""
    rng = np.random.default_rng(11)
    shared = [int(t) for t in rng.integers(0, VOCAB, size=8)]
    out = []
    for i in range(6):
        tail = [int(t) for t in rng.integers(0, VOCAB, size=3 + i % 3)]
        out.append(shared + tail if i % 2 == 0 else tail)
    out.append(list(shared))
    return out


@pytest.mark.parametrize("spec", [0, 3])
def test_prefix_cached_and_speculative_streams_bit_exact(
        lm, lm_params, oracle, spec):
    prompts = _shared_prefix_prompts()
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine, spec_tokens=spec)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=10))
    res = sched.run_to_completion()
    for i, p in enumerate(prompts):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == oracle(p, 10), f"request {i} diverged"
    # the mechanisms actually fired: shared pages were claimed, the
    # full-hit prompt took the CoW rewind, speculation emitted >1/step
    assert sched._prefix_hit_tokens > 0
    st = engine.stats()
    assert st["cow_splits"] >= 1
    assert st["tokens_prefix_cached"] > 0
    if spec:
        assert sched._spec_rows > 0
        assert sched._spec_emitted > sched._spec_rows  # accept_len > 1
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0                  # cached pages only


def test_speculative_sampled_streams_bit_exact(lm, lm_params):
    """Under temperature sampling the acceptance rate drops but the
    streams stay byte-identical: exact-match acceptance replays the
    counter-based RNG at the same positions sequential decode would."""
    prompts = _shared_prefix_prompts()
    sp = SamplingParams(temperature=0.8, top_k=8, seed=5)
    seq = make_engine(lm, lm_params)
    want = [seq.generate(p, 10, sampling=sp) for p in prompts]
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine, spec_tokens=3)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=10, sampling=sp))
    res = sched.run_to_completion()
    for i in range(len(prompts)):
        assert res[i].generated == want[i], f"request {i} diverged"
    assert sched._spec_rows > 0
    engine.kv.assert_consistent()


def test_speculative_survives_pool_pressure_bit_exact(lm, lm_params,
                                                      oracle):
    """Draft page growth is best-effort: when the pool can't hold the
    speculative over-extension the row decodes plainly that step, and
    preemption/recompute still replays the exact stream."""
    engine = make_engine(lm, lm_params, n_blocks=10)
    sched = ContinuousBatchingScheduler(engine, watermark_blocks=0,
                                        spec_tokens=3)
    prompts = prompts_for(4, rng_seed=11)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=p,
                                  max_new_tokens=6))
    res = sched.run_to_completion()
    for i, p in enumerate(prompts):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == oracle(p, 6)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_chunk_recompile_counts_pinned(lm, lm_params):
    """The speculative verify / suffix-prefill chunk program compiles
    once per (batch, chunk, width) bucket: a second identical workload
    on the same engine adds ZERO compiles of any kind."""
    prompts = _shared_prefix_prompts()

    def run(engine):
        sched = ContinuousBatchingScheduler(engine, spec_tokens=3)
        for i, p in enumerate(prompts):
            sched.add_request(Request(request_id=i, prompt=list(p),
                                      max_new_tokens=8))
        sched.run_to_completion()

    engine = make_engine(lm, lm_params)
    run(engine)
    st1 = engine.stats()
    assert st1["chunk_compiles"] == len(st1["chunk_shapes"])
    engine.reset()
    run(engine)
    st2 = engine.stats()
    assert (st2["prefill_compiles"], st2["decode_compiles"],
            st2["chunk_compiles"]) == \
        (st1["prefill_compiles"], st1["decode_compiles"],
         st1["chunk_compiles"])


# ---------------------------------------------------------------------------
# Model-based drafts (layer-truncated self-draft)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampling", [
    SamplingParams(),
    SamplingParams(temperature=0.8, top_k=8, seed=5),
], ids=["greedy", "sampled"])
def test_model_draft_streams_bit_exact(lm, lm_params, sampling):
    """The layer-truncated self-draft proposes instead of the n-gram
    lookup; exact-match acceptance keeps every stream byte-identical to
    the sequential engine under greedy AND temperature/top-k sampling —
    the draft source is a pure throughput decision."""
    prompts = _shared_prefix_prompts()
    seq = make_engine(lm, lm_params)
    want = [seq.generate(p, 8, sampling=sampling) for p in prompts]
    engine = make_engine(lm, lm_params, draft="model")
    sched = ContinuousBatchingScheduler(engine, spec_tokens=3)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=8, sampling=sampling))
    res = sched.run_to_completion()
    for i, w in enumerate(want):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == w, f"request {i} diverged"
    st = engine.stats()
    assert st["draft_source"] == "model"
    assert st["draft_layers"] == 1          # n_layers // 2 of the 2-layer lm
    assert sched._spec_rows_by.get("model", 0) > 0
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_model_draft_under_pool_pressure_and_defrag(lm, lm_params,
                                                    oracle):
    """Acceptance churn: model drafts through a pool small enough to
    force preemption, with defrag while prefix pages are shared —
    every stream still bit-exact, nothing leaked."""
    prompts = _shared_prefix_prompts()
    engine = make_engine(lm, lm_params, n_blocks=14, max_batch=3,
                         draft="model")
    sched = ContinuousBatchingScheduler(engine, watermark_blocks=0,
                                        spec_tokens=3)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=6))
    steps = 0
    while sched.has_work:
        sched.step()
        steps += 1
        if steps % 5 == 0:
            engine.defragment()
            engine.kv.assert_consistent()
        assert steps < 10_000
    res = sched.results()
    for i, p in enumerate(prompts):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == oracle(p, 6)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_model_draft_exact_when_full_depth(lm, lm_params):
    """draft_layers == the target's depth makes the draft the target:
    under greedy every proposal is accepted, so each verify row banks
    spec_tokens + 1 tokens — the upper bound the accept-length gauge
    should sit at."""
    engine = make_engine(lm, lm_params, draft="model", draft_layers=2)
    sched = ContinuousBatchingScheduler(engine, spec_tokens=3)
    p = prompts_for(1, rng_seed=2, lo=8, hi=9)[0]
    sched.add_request(Request(request_id=0, prompt=p,
                              max_new_tokens=9))
    res = sched.run_to_completion()
    assert res[0].state.value == "finished"
    assert sched._spec_emitted == 4 * sched._spec_rows
    assert engine.stats()["draft_layers"] == 2


def test_draft_model_param_subset_and_validation(lm, lm_params):
    """The draft params are references into the target tree — a strict
    subset, never copies — and bad depths are loud."""
    from chainermn_tpu.serving.spec import DraftModel, draft_param_names

    engine = make_engine(lm, lm_params, draft="model")
    dm = engine.draft_model
    assert set(dm.params) == set(draft_param_names(1))
    for name, sub in dm.params.items():
        assert sub is engine.params[name]   # reference, not a copy
    with pytest.raises(ValueError):
        DraftModel(lm, engine.params, 3, ())   # deeper than the target
    with pytest.raises(ValueError):
        DraftModel(lm, engine.params, 0, ())


# ---------------------------------------------------------------------------
# Chunked prefill
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sampling", [
    SamplingParams(),
    SamplingParams(temperature=0.7, top_k=6, seed=9),
], ids=["greedy", "sampled"])
def test_chunked_prefill_streams_bit_exact(lm, lm_params, sampling):
    """Prompts longer than the chunk threshold prefill in scheduler-
    interleaved slices; the first sampled token and every token after
    are byte-identical to monolithic prefill."""
    prompts = prompts_for(4, rng_seed=17, lo=14, hi=30)
    seq = make_engine(lm, lm_params)
    want = [seq.generate(p, 6, sampling=sampling) for p in prompts]
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=6, sampling=sampling))
    res = sched.run_to_completion()
    for i, w in enumerate(want):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == w, f"request {i} diverged"
    assert engine.stats()["prefill_chunk"] == 4
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_chunked_prefill_interleaves_with_decode(lm, lm_params,
                                                 oracle):
    """While a long prompt slices through its prefill, already-running
    requests keep decoding — the whole point of chunking: tokens are
    emitted for the short request during the long one's prefill
    window."""
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    short = prompts_for(1, rng_seed=4, lo=4, hi=5)[0]
    long_p = prompts_for(1, rng_seed=8, lo=28, hi=29)[0]
    shortreq = Request(request_id=0, prompt=short, max_new_tokens=10)
    sched.add_request(shortreq)
    sched.step()                         # short admitted + first token
    sched.add_request(Request(request_id=1, prompt=long_p,
                              max_new_tokens=4))
    sched.step()                         # long admitted -> mid-prefill
    longreq = next(r for r in sched.running if r.request_id == 1)
    assert longreq.prefill_pos is not None
    emitted_during = 0
    while longreq.prefill_pos is not None:
        before = len(shortreq.generated)
        sched.step()
        emitted_during += len(shortreq.generated) - before
    assert emitted_during > 0, "decode starved during chunked prefill"
    res = sched.run_to_completion()
    assert res[0].generated == oracle(short, 10)
    assert res[1].generated == oracle(long_p, 4)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_chunked_prefill_preempted_mid_prefill_recomputes(lm, lm_params,
                                                          oracle):
    """Preempting a mid-prefill victim frees its partially-written
    pages and recomputes the whole prompt on re-admission — the stream
    is still exact."""
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    long_p = prompts_for(1, rng_seed=23, lo=20, hi=21)[0]
    sched.add_request(Request(request_id=0, prompt=long_p,
                              max_new_tokens=5))
    sched.step()
    req = sched.running[0]
    assert req.prefill_pos is not None and req.prefill_pos < len(long_p)
    assert sched._preempt_one()
    assert req.prefill_pos is None and req.preemptions == 1
    res = sched.run_to_completion()
    assert res[0].state.value == "finished", res[0].error
    assert res[0].generated == oracle(long_p, 5)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_chunked_prefill_over_prefix_hit_covers_suffix_only(
        lm, lm_params, oracle):
    """A prefix-cache hit composes with chunking: the slices cover only
    the un-shared suffix, starting exactly at the hit boundary."""
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    shared = prompts_for(1, rng_seed=5, lo=12, hi=13)[0]   # 3 full pages
    sched = ContinuousBatchingScheduler(engine)
    sched.add_request(Request(request_id=0, prompt=list(shared),
                              max_new_tokens=4))
    sched.run_to_completion()            # warm the prefix index
    tail = prompts_for(1, rng_seed=6, lo=10, hi=11)[0]
    p2 = shared + tail
    starts = []
    real_chunk = engine.chunk

    def spy(rows, ids, st):
        starts.append(int(st[0]))
        return real_chunk(rows, ids, st)

    engine.chunk = spy
    try:
        sched2 = ContinuousBatchingScheduler(engine)
        sched2.add_request(Request(request_id=1, prompt=p2,
                                   max_new_tokens=5))
        res = sched2.run_to_completion()
    finally:
        engine.chunk = real_chunk
    assert res[1].state.value == "finished", res[1].error
    assert res[1].generated == oracle(p2, 5)
    assert starts and min(starts) == len(shared), (
        "slices must start at the hit boundary, not re-prefill the "
        f"shared pages (starts={starts})"
    )
    assert sched2._prefix_hit_tokens >= len(shared)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_chunked_prefill_with_model_draft_and_sampling(lm, lm_params):
    """The whole v2 stack at once — chunked prefill + self-draft
    speculation + temperature sampling — still bit-exact."""
    sp = SamplingParams(temperature=0.8, top_k=8, seed=3)
    prompts = prompts_for(3, rng_seed=19, lo=14, hi=26)
    seq = make_engine(lm, lm_params)
    want = [seq.generate(p, 7, sampling=sp) for p in prompts]
    engine = make_engine(lm, lm_params, prefill_chunk=4, draft="model")
    sched = ContinuousBatchingScheduler(engine, spec_tokens=3)
    for i, p in enumerate(prompts):
        sched.add_request(Request(request_id=i, prompt=list(p),
                                  max_new_tokens=7, sampling=sp))
    res = sched.run_to_completion()
    for i, w in enumerate(want):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == w, f"request {i} diverged"
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


# ---------------------------------------------------------------------------
# Long context: streaming prefix registration, bucket growth, sp prefill
# ---------------------------------------------------------------------------
def _slice_spy(engine):
    """Wrap ``engine.chunk`` recording ``(seq_id, start, end)`` per
    non-padding row; returns (calls, original) — restore in finally."""
    calls = []
    real = engine.chunk

    def spy(rows, ids, starts):
        for row, sid, st in zip(rows, ids, starts):
            if int(st) >= 0:
                calls.append((sid, int(st), int(st) + len(row)))
        return real(rows, ids, starts)

    engine.chunk = spy
    return calls, real


def test_streaming_registration_interleaved_doc_prefills_once(
        lm, lm_params, oracle):
    """Two interleaved requests over ONE shared document: each
    completed slice is registered immediately, the trailing request
    adopts it and computes the NEXT slice, so the document's body pages
    are computed exactly once ACROSS the pair (the leapfrog).  Only the
    sub-page tail — where both must sample their own first token — is
    computed twice.  ``stream_prefix=False`` reverts to register-at-
    completion: the document is prefilled twice."""
    doc = prompts_for(1, rng_seed=41, lo=40, hi=41)[0]
    want = oracle(doc, 5)
    page = 4
    body = (len(doc) - 1) // page * page   # the adoptable full pages

    def interleaved(stream):
        engine = make_engine(lm, lm_params, prefill_chunk=4)
        sched = ContinuousBatchingScheduler(engine,
                                            stream_prefix=stream)
        calls, real = _slice_spy(engine)
        try:
            sched.add_request(Request(request_id=0, prompt=list(doc),
                                      max_new_tokens=5))
            sched.step()
            sched.step()        # A mid-prefill, slices registered
            sched.add_request(Request(request_id=1, prompt=list(doc),
                                      max_new_tokens=5))
            res = sched.run_to_completion()
        finally:
            engine.chunk = real
        for i in (0, 1):
            assert res[i].state.value == "finished", res[i].error
            assert res[i].generated == want, f"request {i} diverged"
        engine.kv.assert_consistent()
        assert engine.kv.used_blocks == 0
        return calls, sched

    on_calls, on_sched = interleaved(True)
    cov = [0] * len(doc)
    for _, s, e in on_calls:
        for i in range(s, min(e, len(doc))):
            cov[i] += 1
    assert all(c == 1 for c in cov[:body]), (
        f"document body prefilled more than once: {cov}"
    )
    assert on_sched._stream_hit_tokens > 0

    off_calls, off_sched = interleaved(False)
    assert len(off_calls) > len(on_calls)
    assert on_sched._dup_prefill_slices < off_sched._dup_prefill_slices
    b_on = sum(1 for c in on_calls if c[0] == 1)
    b_off = sum(1 for c in off_calls if c[0] == 1)
    assert b_on < b_off


def test_streaming_registration_survives_preemption(lm, lm_params,
                                                    oracle):
    """A mid-prefill victim's streamed slices stay registered (its
    pages park at refcount 0 in the reusable pool); both its own replay
    and a later request over the same document claim them at admission
    instead of recomputing — and the streams stay exact."""
    doc = prompts_for(1, rng_seed=43, lo=36, hi=37)[0]
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    sched.add_request(Request(request_id=0, prompt=list(doc),
                              max_new_tokens=4))
    for _ in range(3):
        sched.step()
    req = sched.running[0]
    assert req.prefill_pos is not None and req.prefill_pos < len(doc)
    assert sched._preempt_one()
    sched.add_request(Request(request_id=1, prompt=list(doc),
                              max_new_tokens=4))
    res = sched.run_to_completion()
    want = oracle(doc, 4)
    for i in (0, 1):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == want, f"request {i} diverged"
    # admission claimed the preempted request's streamed pages
    assert sched._prefix_hit_tokens > 0
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_streaming_registration_defrag_while_shared(lm, lm_params,
                                                    oracle):
    """Compaction moves pages while two mid-prefill requests share the
    streamed document run — block tables and the prefix index follow
    the permutation, streams stay exact."""
    doc = prompts_for(1, rng_seed=45, lo=40, hi=41)[0]
    engine = make_engine(lm, lm_params, prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    sched.add_request(Request(request_id=0, prompt=list(doc),
                              max_new_tokens=4))
    sched.step()
    sched.step()
    sched.add_request(Request(request_id=1, prompt=list(doc),
                              max_new_tokens=4))
    steps = 0
    while sched.has_work:
        sched.step()
        steps += 1
        if steps % 3 == 0:
            # punch a hole so compaction really moves live pages
            engine.kv.allocate("lo", engine.kv.block_size)
            engine.kv.allocate("hi", engine.kv.block_size)
            engine.kv.free("lo")
            engine.defragment()
            engine.kv.free("hi")
            engine.kv.assert_consistent()
        assert steps < 10_000
    res = sched.results()
    want = oracle(doc, 4)
    for i in (0, 1):
        assert res[i].state.value == "finished", res[i].error
        assert res[i].generated == want, f"request {i} diverged"
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_bucket_ladder_grows_lazily(lm, lm_params, oracle):
    """A prompt past the largest configured prefill bucket no longer
    raises: the ladder grows pow2 rungs (capped at max_len) on first
    use, one compile per new rung, and ``max_bucket`` tracks the
    longest context actually run.  ``max_len_growth=False`` restores
    the hard error."""
    engine = make_engine(lm, lm_params, prefill_buckets=(8,))
    prompt = prompts_for(1, rng_seed=47, lo=20, hi=21)[0]
    assert engine.generate(prompt, 4) == oracle(prompt, 4)
    st = engine.stats()
    assert st["bucket_growths"] >= 2       # 8 -> 16 -> 32
    assert st["max_bucket"] >= len(prompt)
    # grown rungs are cached like configured ones: the same length
    # profile again compiles nothing new
    assert engine.generate(prompt, 4) == oracle(prompt, 4)
    st2 = engine.stats()
    assert st2["prefill_compiles"] == st["prefill_compiles"]
    assert st2["bucket_growths"] == st["bucket_growths"]
    frozen = make_engine(lm, lm_params, prefill_buckets=(8,),
                         max_len_growth=False)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        frozen.generate(prompt, 4)


def test_scheduler_admits_prompts_past_bucket_ladder(lm, lm_params,
                                                     oracle):
    """Satellite of the ladder growth: admission is bounded by max_len
    alone — a prompt longer than every configured bucket flows through
    chunked prefill (its chunk ladder growing as needed) instead of
    failing the request."""
    engine = make_engine(lm, lm_params, prefill_buckets=(8,),
                         chunk_buckets=(2,), prefill_chunk=4)
    sched = ContinuousBatchingScheduler(engine)
    prompt = prompts_for(1, rng_seed=53, lo=40, hi=41)[0]
    sched.add_request(Request(request_id=0, prompt=list(prompt),
                              max_new_tokens=4))
    res = sched.run_to_completion()
    assert res[0].state.value == "finished", res[0].error
    assert res[0].generated == oracle(prompt, 4)
    st = engine.stats()
    assert st["bucket_growths"] >= 1       # chunk ladder 2 -> 4
    assert engine.max_bucket >= len(prompt)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_sp_sharded_prefill_streams_bit_exact(lm, lm_params):
    """sp>1 runs each prefill slice over a sequence-sharded mesh axis;
    the K/V reassembly is a pure concatenation (all_gather, no
    reduction), so streams are byte-identical to the unsharded engine
    under greedy AND sampled decoding — and decode still runs the
    plain collective-free program."""
    prompts = prompts_for(3, rng_seed=59, lo=17, hi=33)
    sampled = SamplingParams(temperature=0.7, top_k=6, seed=5)

    def run(engine):
        sched = ContinuousBatchingScheduler(engine)
        for i, p in enumerate(prompts):
            sched.add_request(Request(
                request_id=i, prompt=list(p), max_new_tokens=5,
                sampling=SamplingParams() if i % 2 else sampled,
            ))
        res = sched.run_to_completion()
        for i in range(len(prompts)):
            assert res[i].state.value == "finished", res[i].error
        return [res[i].generated for i in range(len(prompts))]

    want = run(make_engine(lm, lm_params, prefill_chunk=8))
    for sp in (2, 4):
        engine = make_engine(lm, lm_params, prefill_chunk=8, sp=sp)
        assert run(engine) == want, f"sp={sp} diverged"
        st = engine.stats()
        assert st["sp"] == sp and st["sp_chunk_compiles"] >= 1
        assert st["decode_compiles"] >= 1
        engine.kv.assert_consistent()
        assert engine.kv.used_blocks == 0
    with pytest.raises(ValueError, match="power of two"):
        make_engine(lm, lm_params, sp=3)
    with pytest.raises(ValueError, match="devices"):
        make_engine(lm, lm_params, sp=16)


def test_stream_counters_flow_to_prometheus(lm, lm_params):
    """serve/prefill_stream_hits and serve/dup_prefill_slices reach the
    Reporter as counters and render through the Prometheus exporter."""
    from chainermn_tpu.observability import Reporter
    from chainermn_tpu.tools.obs import to_prometheus

    doc = prompts_for(1, rng_seed=61, lo=40, hi=41)[0]

    def run(stream):
        rep = Reporter()
        engine = make_engine(lm, lm_params, prefill_chunk=4)
        sched = ContinuousBatchingScheduler(engine, reporter=rep,
                                            stream_prefix=stream)
        sched.add_request(Request(request_id=0, prompt=list(doc),
                                  max_new_tokens=4))
        sched.step()
        sched.step()
        sched.add_request(Request(request_id=1, prompt=list(doc),
                                  max_new_tokens=4))
        sched.run_to_completion()
        return rep.summary()

    s_on = run(True)
    assert s_on["counters"]["serve/prefill_stream_hits"] > 0
    prom = to_prometheus(s_on)
    assert 'serve/prefill_stream_hits' in prom
    # with streaming off the duplicate work the counter exists to
    # expose actually happens — and is counted
    s_off = run(False)
    assert s_off["counters"]["serve/dup_prefill_slices"] > 0
    assert 'serve/dup_prefill_slices' in to_prometheus(s_off)


# ---------------------------------------------------------------------------
# Frontend: backpressure, deadlines, streaming
# ---------------------------------------------------------------------------
def test_frontend_backpressure_queue_full(lm, lm_params):
    fe = ServeFrontend(
        ContinuousBatchingScheduler(make_engine(lm, lm_params)),
        max_queue=2,
    )
    p = prompts_for(1)[0]
    fe.submit(p, 4)
    fe.submit(p, 4)
    with pytest.raises(QueueFull):
        fe.submit(p, 4)
    fe.step()                            # admission drains the queue
    fe.submit(p, 4)                      # now accepted
    fe.run_until_idle()


def test_frontend_timeout_fake_clock(lm, lm_params, oracle):
    now = [0.0]
    fe = ServeFrontend(
        ContinuousBatchingScheduler(make_engine(lm, lm_params)),
        clock=lambda: now[0],
    )
    prompts = prompts_for(2, rng_seed=5)
    h_ok = fe.submit(prompts[0], 4)
    h_to = fe.submit(prompts[1], 40, timeout_s=0.5)
    fe.step()
    now[0] = 1.0                         # h_to's deadline passes
    fe.run_until_idle()
    assert h_ok.status == "finished"
    assert h_ok.tokens == oracle(prompts[0], 4)
    assert h_to.status == "timeout" and h_to.done
    assert h_to.error == "deadline exceeded"
    with pytest.raises(TimeoutError):
        fe.result(h_to)
    # the evicted sequence's pages were reclaimed
    fe.scheduler.engine.kv.assert_consistent()
    assert fe.scheduler.engine.kv.used_blocks == 0
    assert h_ok.latency_s is not None and h_ok.latency_s >= 0


def test_frontend_streaming_matches_final_tokens(lm, lm_params):
    fe = ServeFrontend(
        ContinuousBatchingScheduler(make_engine(lm, lm_params)),
    )
    streamed = {}
    handles = [
        fe.submit(p, 5, on_token=lambda rid, tok:
                  streamed.setdefault(rid, []).append(tok))
        for p in prompts_for(3, rng_seed=9)
    ]
    fe.run_until_idle()
    for h in handles:
        assert h.status == "finished"
        assert streamed[h.request_id] == h.tokens
        assert len(h.tokens) == 5


def test_frontend_temperature_stream_independent_of_batching(lm,
                                                             lm_params):
    """Seeded temperature sampling: the stream must not depend on what
    else shares the batch — run the same request alone and among
    neighbors."""
    sp = SamplingParams(temperature=0.7, top_k=8, seed=42)
    prompt = prompts_for(1, rng_seed=13)[0]

    def run(extra):
        fe = ServeFrontend(
            ContinuousBatchingScheduler(make_engine(lm, lm_params)),
        )
        h = fe.submit(prompt, 6, sampling=sp)
        for q in extra:
            fe.submit(q, 6, sampling=SamplingParams(temperature=1.3,
                                                    seed=1))
        fe.run_until_idle()
        return h.tokens

    alone = run([])
    crowded = run(prompts_for(3, rng_seed=17))
    assert alone == crowded


# ---------------------------------------------------------------------------
# Collective-free decode: pinned HLO census
# ---------------------------------------------------------------------------
def _decode_census() -> dict:
    from chainermn_tpu.analysis.fixtures import fixture_serving_decode
    from chainermn_tpu.observability import audit_fn

    t = fixture_serving_decode()
    audit = audit_fn(t["fn"], *t["args"])
    return {
        "target": t["target"],
        "hlo_collectives": audit.census(),
        "reduction_collectives": audit.reduction_collectives(),
        "per_axis_operand_bytes": dict(
            sorted(audit.bytes_per_axis.items())
        ),
    }


def test_decode_step_collective_census_matches_golden():
    with open(CENSUS_GOLDEN_PATH) as f:
        golden = json.load(f)
    current = _decode_census()
    assert current == golden, (
        "decode-step collective census drifted — a psum crept into the "
        "per-sequence data plane?  If intended (it should not be), "
        f"regenerate with: python {__file__} --regen"
    )
    # the golden itself must pin ZERO collectives (guards a bad regen)
    assert golden["reduction_collectives"] == 0
    assert all(v == 0 for v in golden["hlo_collectives"].values())
    assert golden["per_axis_operand_bytes"] == {}


def _sp_prefill_census() -> dict:
    from chainermn_tpu.analysis.fixtures import fixture_sharded_prefill
    from chainermn_tpu.observability import audit_fn

    t = fixture_sharded_prefill()
    audit = audit_fn(t["fn"], *t["args"])
    return {
        "target": t["target"],
        "hlo_collectives": audit.census(),
        "reduction_collectives": audit.reduction_collectives(),
    }


def test_sp_prefill_collective_census_matches_golden():
    """The sequence-sharded prefill program's collective budget is
    pinned: exactly the per-layer K/V all-gathers (pure concatenation),
    ZERO reduction collectives — the shape of the bit-exactness
    argument, enforced on the compiled HLO."""
    with open(SP_CENSUS_GOLDEN_PATH) as f:
        golden = json.load(f)
    current = _sp_prefill_census()
    assert current == golden, (
        "sp-prefill collective census drifted — if a reduction crept "
        "in, the serving plane's bit-exactness contract is broken; if "
        "the change is an intended gather restructure, regenerate "
        f"with: python {__file__} --regen"
    )
    # the golden itself must pin gathers-only (guards a bad regen)
    assert golden["reduction_collectives"] == 0
    assert golden["hlo_collectives"]["all_gather"] > 0
    assert all(v == 0 for k, v in golden["hlo_collectives"].items()
               if k != "all_gather")


def _tp_decode_census() -> dict:
    """Census of the tensor-parallel decode step's COMPILED HLO.

    The ``tp`` plan shards by NamedSharding annotation, so its
    collectives exist only after GSPMD partitioning — ``audit_fn``'s
    jaxpr view sees zero.  The per-layer count is pinned by differencing
    a 2-layer program against the 1-layer one, and the sampling tail
    (argmax over the replicated fp32 logits) is audited separately: the
    leader samples locally, so the tail must stay collective-free."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from chainermn_tpu.analysis.fixtures import fixture_tp_decode
    from chainermn_tpu.observability import audit_compiled

    assert len(jax.devices()) >= 2, "TP census needs >= 2 devices"
    audits = {}
    for n_layers in (1, 2):
        t = fixture_tp_decode(n_layers=n_layers)
        audits[n_layers] = audit_compiled(t["fn"], *t["args"])
    c1, c2 = audits[1].census(), audits[2].census()
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("model",))
    logits = jax.ShapeDtypeStruct(
        (2, VOCAB), jnp.float32,
        sharding=NamedSharding(mesh, PartitionSpec()),
    )
    tail = audit_compiled(
        jax.jit(lambda x: jnp.argmax(x, axis=-1).astype(jnp.int32)),
        logits,
    )
    return {
        "target": "tp_decode",
        "hlo_collectives": c1,
        "per_layer_collectives": {k: c2[k] - c1[k] for k in sorted(c1)},
        "reduction_collectives": audits[1].reduction_collectives(),
        "sampling_tail_collectives": tail.census(),
        "sampling_tail_reduction_collectives": tail.reduction_collectives(),
    }


def test_tp_decode_collective_census_matches_golden():
    """The TP decode step's wire cost is pinned at the compiled-HLO
    level: exactly two all-reduces per layer (attention out-projection
    and FFN down-projection — the canonical Megatron-style partition),
    no gathers or permutes, and a collective-free sampling tail.  Any
    drift means GSPMD stopped partitioning the decode step the way the
    shard-group design assumes."""
    with open(TP_CENSUS_GOLDEN_PATH) as f:
        golden = json.load(f)
    current = _tp_decode_census()
    assert current == golden, (
        "tp-decode collective census drifted — the GSPMD partition of "
        "the shard-group decode step changed.  If the new lowering is "
        "intended (check the per-layer count stayed O(1)), regenerate "
        f"with: python {__file__} --regen"
    )
    # the golden itself must pin the Megatron shape (guards a bad regen)
    per_layer = golden["per_layer_collectives"]
    assert per_layer["psum"] == 2
    assert all(v == 0 for k, v in per_layer.items() if k != "psum")
    assert golden["reduction_collectives"] > 0
    # sampling must never pay for the tensor parallelism
    assert golden["sampling_tail_reduction_collectives"] == 0
    assert all(
        v == 0 for v in golden["sampling_tail_collectives"].values()
    )


# ---------------------------------------------------------------------------
# Subprocess smokes: bench --serve, the example
# ---------------------------------------------------------------------------
def test_bench_serve_emits_decode_throughput_json():
    from conftest import subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--serve",
         "--lm-vocab", "32", "--lm-d-model", "16", "--lm-heads", "2",
         "--lm-d-ff", "32", "--lm-layers", "1",
         "--serve-batch-sizes", "1,2", "--serve-requests", "3",
         "--serve-prompt-len", "6", "--serve-new-tokens", "4",
         "--serve-block-size", "4", "--serve-blocks", "32",
         "--serve-max-len", "32"],
        capture_output=True, text=True, timeout=420,
        env=subprocess_env(n_devices=1), cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    # same report shape as the train benches: metric/value/unit headline
    assert out["unit"] == "tokens/sec" and out["value"] > 0
    assert "decode" in out["metric"]
    assert [r["batch_size"] for r in out["sweep"]] == [1, 2]
    for row in out["sweep"]:
        assert row["finished"] == row["requests"] == 3
        assert row["tokens_per_sec"] > 0
        assert row["p50_token_latency_ms"] is not None
        assert row["p99_token_latency_ms"] >= row["p50_token_latency_ms"]


def test_bench_serve_tp_emits_group_size_curve():
    """--serve-tp rides along additively: the usual --serve report plus
    a "tp" section whose curve covers every valid group size with a
    speedup relative to the K=1 baseline, and sizes the local device
    count can't host reported as skipped, not dropped."""
    from conftest import subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--serve",
         "--serve-tp", "--serve-tp-sizes", "1,2,4",
         "--lm-vocab", "32", "--lm-d-model", "16", "--lm-heads", "2",
         "--lm-d-ff", "32", "--lm-layers", "1",
         "--serve-batch-sizes", "2", "--serve-requests", "3",
         "--serve-prompt-len", "6", "--serve-new-tokens", "4",
         "--serve-block-size", "4", "--serve-blocks", "32",
         "--serve-max-len", "32"],
        capture_output=True, text=True, timeout=420,
        env=subprocess_env(n_devices=2), cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    tp = out["tp"]
    assert tp["devices"] == 2
    assert [r["group_size"] for r in tp["curve"]] == [1, 2]
    for r in tp["curve"]:
        assert r["finished"] == 3 and r["tokens_per_sec"] > 0
        assert r["speedup"] > 0
    # K=4 exceeds both devices and head count: reported, not dropped
    assert [s["group_size"] for s in tp["skipped"]] == [4]


def test_serve_lm_example_smoke():
    from conftest import subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "examples", "serve_lm", "serve_lm.py"),
         "--train-steps", "2", "--requests", "3", "--new-tokens", "4",
         "--n-blocks", "32", "--d-model", "16", "--d-ff", "32"],
        capture_output=True, text=True, timeout=420,
        env=subprocess_env(n_devices=1), cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "req 0:" in proc.stdout and "gauges:" in proc.stdout


# ---------------------------------------------------------------------------
# Soak (auto-marked slow by conftest): eviction + defrag churn
# ---------------------------------------------------------------------------
def test_serving_soak_eviction_defrag_churn(lm, lm_params, oracle):
    engine = make_engine(lm, lm_params, n_blocks=12, max_batch=3)
    sched = ContinuousBatchingScheduler(engine, watermark_blocks=0)
    fe = ServeFrontend(sched, max_queue=64)
    prompts = prompts_for(24, rng_seed=23, lo=3, hi=15)
    handles = [fe.submit(p, 5) for p in prompts]
    steps = 0
    while sched.has_work:
        fe.step()
        steps += 1
        if steps % 7 == 0:
            engine.defragment()          # churn the page layout
            engine.kv.assert_consistent()
        assert steps < 10_000
    for h, p in zip(handles, prompts):
        assert h.status == "finished", h.error
        assert h.tokens == oracle(p, 5)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0


def test_serving_soak_shared_prefix_spec_churn(lm, lm_params, oracle):
    """Soak (auto-marked slow): duplicate-prefix traffic + speculative
    decoding through a pool small enough to force cached-page eviction,
    CoW splits, preemption and defrag churn at once — every stream
    still bit-exact, no page leaked or double-freed."""
    engine = make_engine(lm, lm_params, n_blocks=14, max_batch=3)
    sched = ContinuousBatchingScheduler(engine, watermark_blocks=0,
                                        spec_tokens=3)
    fe = ServeFrontend(sched, max_queue=64)
    rng = np.random.default_rng(29)
    shared = [int(t) for t in rng.integers(0, VOCAB, size=8)]
    prompts = []
    for i, p in enumerate(prompts_for(18, rng_seed=31, lo=3, hi=9)):
        prompts.append(shared + p if i % 2 == 0 else p)
    handles = [fe.submit(p, 5) for p in prompts]
    steps = 0
    while sched.has_work:
        fe.step()
        steps += 1
        if steps % 7 == 0:
            engine.defragment()
            engine.kv.assert_consistent()
        assert steps < 10_000
    for h, p in zip(handles, prompts):
        assert h.status == "finished", h.error
        assert h.tokens == oracle(p, 5)
    engine.kv.assert_consistent()
    assert engine.kv.used_blocks == 0
    assert sched._prefix_hit_tokens > 0  # sharing really was in play


# ---------------------------------------------------------------------------
# --regen
# ---------------------------------------------------------------------------
def _regen():
    # Outside pytest, conftest's device-count flag hasn't run; set it
    # before the first backend touch or the tp mesh degenerates to one
    # device and the TP census regenerates as all-zero.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        )
    jax.config.update("jax_platforms", "cpu")
    os.makedirs(os.path.dirname(CENSUS_GOLDEN_PATH), exist_ok=True)
    for path, census in ((CENSUS_GOLDEN_PATH, _decode_census()),
                         (SP_CENSUS_GOLDEN_PATH, _sp_prefill_census()),
                         (TP_CENSUS_GOLDEN_PATH, _tp_decode_census())):
        with open(path, "w") as f:
            json.dump(census, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {path}", file=sys.stderr)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--regen", action="store_true",
                    help="regenerate the decode-census golden")
    if not ap.parse_args().regen:
        ap.error("run under pytest, or pass --regen to regenerate")
    _regen()


# ---------------------------------------------------------------------------
# Per-tenant KV page-seconds: exact residency integrals
# ---------------------------------------------------------------------------


def test_kv_page_seconds_conservation():
    """With an injectable clock, per-tenant residency integrals are
    exact, shared prefix pages bill their FIRST owner only, untenanted
    holdings stay in the pool integral, and the sum of all owner
    buckets equals the pool integral through alloc / extend / share /
    truncate / free / defragment."""
    t = [0.0]
    kv = PagedKVCache(n_blocks=16, block_size=4, clock=lambda: t[0])

    kv.allocate("a", 8, tenant="ta")        # 2 pages, ta
    t[0] = 5.0                              # ta: 2pg x 5s = 10
    kv.allocate("b", 4, tenant="tb")        # 1 page, tb
    t[0] = 7.0                              # ta +4, tb +2
    kv.extend("a", 12)                      # ta now holds 3 pages
    t[0] = 10.0                             # ta +9, tb +3
    ps = kv.page_seconds()
    assert ps == {"ta": pytest.approx(23.0), "tb": pytest.approx(5.0)}
    assert kv.pool_page_seconds() == pytest.approx(28.0)

    # tb shares ta's registered prefix: the 3 shared pages keep
    # accruing to ta (first owner), only tb's fresh page bills tb
    toks = list(range(12))
    kv.register_prefix("a", toks)
    shared = kv.match_prefix(toks)
    assert len(shared) == 3
    kv.allocate("c", 14, prefix_pages=shared, tenant="tb")
    t[0] = 12.0                             # ta +6, tb +2+2
    ps = kv.page_seconds()
    assert ps == {"ta": pytest.approx(29.0), "tb": pytest.approx(9.0)}
    assert kv.pool_page_seconds() == pytest.approx(sum(ps.values()))
    kv.assert_consistent()

    # untenanted holdings: excluded from the tenant map, in the pool
    kv.allocate("d", 4)                     # 1 page, owner None
    t[0] = 14.0                             # ta +6, tb +4, None +2
    ps = kv.page_seconds()
    assert set(ps) == {"ta", "tb"}
    assert kv.pool_page_seconds() == pytest.approx(sum(ps.values()) + 2.0)

    # freeing the first owner does NOT re-bill still-shared pages: a's
    # pages stay held under ta while c references them
    kv.free("a")
    kv.free("d")
    t[0] = 16.0                             # ta +6, tb +4
    ps = kv.page_seconds()
    assert ps == {"ta": pytest.approx(41.0), "tb": pytest.approx(17.0)}
    assert kv.pool_page_seconds() == pytest.approx(sum(ps.values()) + 2.0)

    # owners survive page renumbering
    kv.defragment()
    kv.assert_consistent()
    t[0] = 18.0                             # ta +6, tb +4
    kv.truncate("c", 12)                    # releases tb's fresh page
    t[0] = 20.0                             # ta +6, tb +2 (b only)
    ps = kv.page_seconds()
    assert ps == {"ta": pytest.approx(53.0), "tb": pytest.approx(23.0)}

    # all sequences gone: the meter stops (cached refcount-0 prefix
    # pages are reclaimable capacity, not tenant residency)
    kv.free("b")
    kv.free("c")
    t[0] = 100.0
    assert kv.page_seconds() == {"ta": pytest.approx(53.0),
                                 "tb": pytest.approx(23.0)}
    assert kv.pool_page_seconds() == pytest.approx(53.0 + 23.0 + 2.0)
    kv.assert_consistent()


def test_kv_page_seconds_scheduler_attribution(lm, lm_params):
    """Request.tenant flows scheduler -> kv.allocate: the scheduler's
    end-of-step gauges publish per-tenant page-seconds that sum to the
    pool integral when every request is tenanted."""
    from chainermn_tpu.observability.reporter import Reporter

    reporter = Reporter()
    engine = make_engine(lm, lm_params)
    sched = ContinuousBatchingScheduler(engine, reporter=reporter)
    sched.add_request(Request(request_id=0, prompt=[1, 2, 3, 4, 5],
                              max_new_tokens=4, tenant="ta"))
    sched.add_request(Request(request_id=1, prompt=[6, 7, 8],
                              max_new_tokens=4, tenant="tb"))
    sched.run_to_completion()
    ps = engine.kv.page_seconds()
    assert set(ps) == {"ta", "tb"}
    assert sum(ps.values()) == pytest.approx(
        engine.kv.pool_page_seconds())
    g = reporter.summary()["gauges"]
    assert g["tenant/ta/kv_page_seconds"]["value"] == pytest.approx(
        ps["ta"])
    # tokens emitted under each tenant were counted as they streamed
    c = reporter.summary()["counters"]
    assert c["tenant/ta/tokens_out"] == 4
    assert c["tenant/tb/tokens_out"] == 4
    engine.kv.assert_consistent()
